"""Python/JAX UDF subsystem (matrixone_tpu/udf): CREATE FUNCTION
surface, sandbox, execution tiers, durability + replication through the
DDL funnel, serving-cache interplay, and worker offload.

Reference analogue: pkg/udf/pythonservice tests + the
mo_user_defined_function catalog semantics."""

import json
import os
import tempfile

import numpy as np
import pytest

from matrixone_tpu.frontend import Session
from matrixone_tpu.sql.binder import BindError
from matrixone_tpu.storage.engine import Engine
from matrixone_tpu.storage.fileservice import MemoryFS


@pytest.fixture
def sess():
    s = Session()
    s.execute("create table t (a bigint, b double)")
    s.execute("insert into t values (1, 1.5), (2, 2.5), (3, 3.5), "
              "(4, null)")
    yield s
    s.close()


def _mk(s, name="f", body="x * 2.0 + y", props="", aggregate=False,
        args="(x DOUBLE, y BIGINT)", ret="DOUBLE", replace=False):
    kw = "aggregate function" if aggregate else "function"
    rep = "or replace " if replace else ""
    s.execute(f"create {rep}{kw} {name}{args} returns {ret} "
              f"language python {props} as $$ {body} $$")


# ------------------------------------------------------------- surface

def test_scalar_udf_jit_tier_and_nulls(sess):
    _mk(sess)
    r = sess.execute("select f(b, a) from t")
    assert r.rows() == [(4.0,), (7.0,), (10.0,), (None,)]
    # EXPLAIN names the call and its tier
    txt = sess.execute("explain select f(b, a) from t").text
    assert "UdfCall f [jit]" in txt
    # usable inside WHERE too
    r = sess.execute("select a from t where f(b, a) > 5")
    assert [x[0] for x in r.rows()] == [2, 3]


def test_udf_arg_coercion_and_arity(sess):
    _mk(sess, name="sq", body="x * x", args="(x DOUBLE)")
    # BIGINT column coerces into the declared DOUBLE parameter
    r = sess.execute("select sq(a) from t where a = 3")
    assert r.rows() == [(9.0,)]
    with pytest.raises(BindError, match="takes 1 argument"):
        sess.execute("select sq(a, b) from t")


def test_row_tier_fallback_for_nontraceable_body(sess):
    from matrixone_tpu.utils import metrics as M
    _mk(sess, name="steppy", args="(x DOUBLE)",
        body="if x > 2.0:\n    return x * 10.0\nreturn x")
    rows0 = M.udf_rows.get(tier="row")
    r = sess.execute("select steppy(b) from t")
    assert r.rows() == [(1.5,), (25.0,), (35.0,), (None,)]
    # data-dependent control flow cannot trace: counted in the row tier
    assert M.udf_rows.get(tier="row") > rows0
    assert "UdfCall steppy [row]" in sess.execute(
        "explain select steppy(b) from t").text


def test_aggregate_udf(sess):
    _mk(sess, name="sumsq", body="jnp.sum(x * x)", args="(x DOUBLE)",
        aggregate=True)
    r = sess.execute("select sumsq(b) from t")
    assert r.rows()[0][0] == pytest.approx(1.5**2 + 2.5**2 + 3.5**2)
    # WHERE filters feed the aggregate; NULL rows are skipped
    r = sess.execute("select sumsq(b) from t where a < 3")
    assert r.rows()[0][0] == pytest.approx(1.5**2 + 2.5**2)
    with pytest.raises(BindError, match="GROUP BY"):
        sess.execute("select a, sumsq(b) from t group by a")


def test_aggregate_udf_limit_offset_order_by(sess):
    # the one-row reduction still honors LIMIT/OFFSET (LIMIT 0 must
    # yield zero rows, not a silently ignored clause); ORDER BY is
    # rejected cleanly rather than dropped
    _mk(sess, name="tot", body="jnp.sum(x)", args="(x DOUBLE)",
        aggregate=True)
    assert sess.execute("select tot(b) from t limit 0").rows() == []
    assert sess.execute(
        "select tot(b) from t limit 5 offset 1").rows() == []
    assert len(sess.execute("select tot(b) from t limit 5").rows()) == 1
    with pytest.raises(BindError, match="ORDER BY"):
        sess.execute("select tot(b) from t order by 1")


def test_unbounded_loops_are_out_of_dialect(sess):
    # `while` would be un-interruptible (deadlines fire BETWEEN rows)
    with pytest.raises(BindError, match="While is not allowed"):
        _mk(sess, name="spin", args="(x DOUBLE)",
            body="while True:\n    pass\nreturn 0.0")
    # range() is capped so `for` trip counts stay bounded
    _mk(sess, name="bigr", body="float(len(range(int(x))))",
        args="(x DOUBLE)", props="properties ('vectorized'='false')")
    assert sess.execute(
        "select bigr(b) from t where a = 1").rows() == [(1.0,)]
    sess.execute("insert into t values (9, 1e9)")
    with pytest.raises(ValueError, match="loop cap"):
        sess.execute("select bigr(b) from t where a = 9")


def test_row_tier_overflow_is_clean(sess):
    # a body returning 2**70 into a BIGINT result must surface as a
    # clean udf error (coercion inside the row-loop try), never a raw
    # numpy OverflowError traceback
    _mk(sess, name="toobig", body="2 ** 70", args="(x DOUBLE)",
        ret="BIGINT", props="properties ('vectorized'='false')")
    with pytest.raises(ValueError, match="udf 'toobig'"):
        sess.execute("select toobig(b) from t where a = 1")


def test_create_or_replace_and_drop(sess):
    _mk(sess, name="g", body="x + 1.0", args="(x DOUBLE)")
    assert sess.execute("select g(b) from t where a=1").rows() == [(2.5,)]
    with pytest.raises(BindError, match="already exists"):
        _mk(sess, name="g", body="x + 2.0", args="(x DOUBLE)")
    _mk(sess, name="g", body="x + 2.0", args="(x DOUBLE)", replace=True)
    assert sess.execute("select g(b) from t where a=1").rows() == [(3.5,)]
    rows = sess.execute("show functions").rows()
    assert any(r[0] == "g" for r in rows)
    sess.execute("drop function g")
    with pytest.raises(BindError, match="unknown function"):
        sess.execute("select g(b) from t")
    with pytest.raises(BindError, match="no such function"):
        sess.execute("drop function g")
    sess.execute("drop function if exists g")      # no-op, no error


def test_or_replace_arg_reorder_misses_compile_cache(sess):
    # same body text, same dtypes, swapped parameter names: arg_names
    # participate in body_hash, so the compile cache must MISS — the
    # compiled function binds call arguments positionally by these names
    _mk(sess, name="d", body="x - y", args="(x DOUBLE, y DOUBLE)")
    assert sess.execute(
        "select d(b, a) from t where a=2").rows() == [(0.5,)]
    _mk(sess, name="d", body="x - y", args="(y DOUBLE, x DOUBLE)",
        replace=True)
    # the first parameter is now y: d(b, a) computes x - y = a - b
    assert sess.execute(
        "select d(b, a) from t where a=2").rows() == [(-0.5,)]


def test_row_tier_skips_filtered_rows(sess):
    # a row the WHERE already excluded must never reach a row-loop body:
    # the jit tier computes masked rows harmlessly in-vector (inf), but
    # per-row Python on b=0.0 would raise ZeroDivisionError and kill the
    # query for a row the user's predicate explicitly excluded
    sess.execute("insert into t values (5, 0.0)")
    _mk(sess, name="inv", body="1.0 / x", args="(x DOUBLE)",
        props="properties ('vectorized'='false')")
    r = sess.execute("select inv(b) from t where b <> 0")
    assert sorted(x[0] for x in r.rows()) == sorted(
        [1 / 1.5, 1 / 2.5, 1 / 3.5])


def test_udf_catalog_table_is_queryable(sess):
    _mk(sess, name="q1f", body="x", args="(x DOUBLE)", ret="DOUBLE")
    r = sess.execute(
        "select name, kind from system_udf where name = 'q1f'")
    assert r.rows() == [("q1f", "scalar")]


def test_sandbox_rejections(sess):
    for body, msg in [
            ("import os\nreturn 1.0", "Import"),
            ("().__class__", "__class__"),
            ("open('/etc/passwd')", "'open'"),
            ("x.__dict__", "__dict__"),
            ("getattr(x, 'foo')", "'getattr'"),
            # the np/jnp modules are real: their file-I/O surface is
            # denied by attribute name, else "no open" is a lie
            ("np.fromfile('/etc/passwd', dtype=np.uint8).sum() + x",
             "fromfile"),
            ("(x * 0).tofile('/tmp/pwn')\nreturn x", "tofile"),
            ("np.lib.format.open_memmap('/tmp/pwn')", "'lib'"),
            ("jnp.save('/tmp/pwn', x)\nreturn x", "'save'"),
    ]:
        with pytest.raises(BindError, match="not allowed"):
            _mk(sess, name="evil", body=body, args="(x DOUBLE)")
    # broken bodies fail at CREATE, not first call
    with pytest.raises(BindError, match="does not parse"):
        _mk(sess, name="broken", body="x +* 2", args="(x DOUBLE)")
    # reserved names cannot be shadowed
    with pytest.raises(BindError, match="shadows a builtin"):
        _mk(sess, name="abs", body="x", args="(x DOUBLE)")
    # non-numeric arg/result types are out of dialect
    with pytest.raises(BindError, match="must be numeric"):
        _mk(sess, name="sfn", body="x", args="(x VARCHAR(8))",
            ret="DOUBLE")


def test_runtime_error_is_clean(sess):
    # name errors only surface at call time (jit trace AND row tier
    # agree); the session sees a UdfError-derived message, no traceback
    _mk(sess, name="oops", body="x + undefined_name", args="(x DOUBLE)")
    with pytest.raises(ValueError, match="udf 'oops'"):
        sess.execute("select oops(b) from t")


# ------------------------------------------ durability and replication

def test_udf_survives_restart_via_wal_and_checkpoint():
    fs = MemoryFS()
    eng = Engine(fs)
    s = Session(catalog=eng)
    s.execute("create table r (x double)")
    s.execute("insert into r values (2.0), (3.0)")
    _mk(s, name="dbl", body="x * 2.0", args="(x DOUBLE)")
    # WAL-tail replay (no checkpoint yet)
    eng2 = Engine.open(fs, wal=None)
    s2 = Session(catalog=eng2)
    assert s2.execute("select dbl(x) from r").rows() == [(4.0,), (6.0,)]
    # checkpoint -> manifest restore path
    eng2.checkpoint()
    eng3 = Engine.open(fs, wal=None)
    s3 = Session(catalog=eng3)
    assert s3.execute("select dbl(x) from r").rows() == [(4.0,), (6.0,)]
    assert any(r[0] == "dbl" for r in
               s3.execute("show functions").rows())


def test_udf_replicates_to_cn_replica():
    from matrixone_tpu.cluster import RemoteCatalog, TNService
    d = tempfile.mkdtemp(prefix="mo_udf_cn_")
    tn = TNService(data_dir=d).start()
    cat1 = RemoteCatalog(("127.0.0.1", tn.port), data_dir=d)
    cat2 = RemoteCatalog(("127.0.0.1", tn.port), data_dir=d)
    try:
        s1, s2 = Session(catalog=cat1), Session(catalog=cat2)
        s1.execute("create table rt (x double)")
        s1.execute("insert into rt values (5.0)")
        _mk(s1, name="half", body="x / 2.0", args="(x DOUBLE)")
        ts = max(cat1.committed_ts, cat2.committed_ts)
        for c in (cat1, cat2):
            c.consumer.wait_ts(ts)
        # the OTHER CN resolves and executes the function locally
        assert s2.execute("select half(x) from rt").rows() == [(2.5,)]
        g_before = cat2.ddl_gen
        s1.execute("drop function half")
        ts = cat1.committed_ts
        cat2.consumer.wait_ts(ts)
        # replica ddl_gen bumped by the logtail system_udf delete
        assert cat2.ddl_gen > g_before
        with pytest.raises(BindError, match="unknown function"):
            s2.execute("select half(x) from rt")
    finally:
        cat1.close()
        cat2.close()
        tn.stop()


def test_udf_is_tenant_scoped():
    """Each account's functions live in its own `acct$system_udf`
    namespace (ScopedCatalog prefixes the catalog table like any
    other): no cross-tenant visibility in either direction."""
    from matrixone_tpu.frontend.auth import AccountManager
    eng = Engine()
    mgr = AccountManager(eng)
    mgr.create_account("acme", "adm", "pw", False)
    s = Session(catalog=eng, auth=mgr.context_for("acme", "adm"),
                auth_manager=mgr)
    s.execute("create table t (x double)")
    s.execute("insert into t values (2.0)")
    _mk(s, name="triple", body="x * 3.0", args="(x DOUBLE)")
    assert s.execute("select triple(x) from t").rows() == [(6.0,)]
    assert "acme$system_udf" in eng.tables
    root = Session(catalog=eng)
    assert root.execute("show functions").rows() == []
    root.execute("create table rt2 (x double)")
    root.execute("insert into rt2 values (1.0)")
    with pytest.raises(BindError, match="unknown function"):
        root.execute("select triple(x) from rt2")


# -------------------------------------------------- serving interplay

def test_drop_function_invalidates_cached_plan():
    from matrixone_tpu.serving import serving_for
    eng = Engine()
    s = Session(catalog=eng)
    sv = serving_for(eng)
    plan_was = sv.plan_cache.enabled
    sv.plan_cache.enabled = True
    sv.clear()
    try:
        s.execute("create table pc (a bigint, b double)")
        s.execute("insert into pc values (1, 2.0)")
        _mk(s, name="pf", body="x * 3.0", args="(x DOUBLE)")
        q = "select pf(b) from pc where a = 1"
        from matrixone_tpu.utils import metrics as M
        for _ in range(3):      # note -> activate+store -> hit
            assert s.execute(q).rows() == [(6.0,)]
        hits0 = M.plan_cache_ops.get(outcome="hit")
        assert s.execute(q).rows() == [(6.0,)]
        assert M.plan_cache_ops.get(outcome="hit") > hits0
        g0 = eng.ddl_gen
        s.execute("drop function pf")
        assert eng.ddl_gen > g0          # the system_udf commit IS DDL
        # the cached plan must NOT serve the dropped function
        with pytest.raises(BindError, match="unknown function"):
            s.execute(q)
        # ... and OR REPLACE must re-bind to the NEW body, not the
        # cached plan's snapshot
        _mk(s, name="pf", body="x * 3.0", args="(x DOUBLE)")
        for _ in range(3):
            assert s.execute(q).rows() == [(6.0,)]
        _mk(s, name="pf", body="x * 5.0", args="(x DOUBLE)",
            replace=True)
        assert s.execute(q).rows() == [(10.0,)]
    finally:
        sv.plan_cache.enabled = plan_was
        sv.clear()


def test_nondeterministic_udf_bypasses_result_cache():
    from matrixone_tpu.serving import serving_for
    eng = Engine()
    s = Session(catalog=eng)
    sv = serving_for(eng)
    mb_was = sv.result_cache.max_bytes
    sv.result_cache.max_bytes = 16 << 20
    sv.clear()
    try:
        s.execute("create table nd (x double)")
        s.execute("insert into nd values (0.0)")
        _mk(s, name="noisy", args="(x DOUBLE)",
            body="x + np.random.uniform(0.0, 1e6)",
            props="properties ('deterministic'='false',"
                  "'vectorized'='false')")
        q = "select noisy(x) from nd"
        vals = {s.execute(q).rows()[0][0] for _ in range(4)}
        # a result-cache hit would collapse these to one value
        assert len(vals) > 1
        # deterministic UDFs DO cache
        _mk(s, name="calm", args="(x DOUBLE)", body="x + 41.0")
        qc = "select calm(x) from nd"
        from matrixone_tpu.utils import metrics as M
        h0 = M.result_cache_ops.get(outcome="hit")
        for _ in range(3):
            assert s.execute(qc).rows() == [(41.0,)]
        assert M.result_cache_ops.get(outcome="hit") > h0
    finally:
        sv.result_cache.max_bytes = mb_was
        sv.clear()


# ------------------------------------------------------ worker offload

@pytest.fixture
def offload(monkeypatch):
    from matrixone_tpu.udf import executor as uexec
    from matrixone_tpu.worker import TpuWorkerServer
    srv = TpuWorkerServer(port=0).start()
    monkeypatch.setenv("MO_UDF_OFFLOAD", "1")
    monkeypatch.setenv("MO_UDF_WORKER", f"127.0.0.1:{srv.port}")
    yield srv
    uexec.reset_clients()
    srv.stop()


@pytest.mark.chaos
def test_offload_bit_identical_and_fallback(sess, offload, monkeypatch):
    from matrixone_tpu.utils import metrics as M
    _mk(sess, name="rf", body="x * 1.5 + y", args="(x DOUBLE, y BIGINT)")
    q = "select rf(b, a) from t"
    ok0 = M.udf_offload.get(outcome="ok")
    remote = sess.execute(q).rows()
    assert M.udf_offload.get(outcome="ok") > ok0
    assert "UdfCall rf [remote]" in sess.execute(f"explain {q}").text
    monkeypatch.setenv("MO_UDF_OFFLOAD", "0")
    local = sess.execute(q).rows()
    # remote and local are the SAME jitted body: bit-identical
    assert remote == local
    # worker dies mid-workload: the next call retries, then falls back
    # to local evaluation with identical results
    monkeypatch.setenv("MO_UDF_OFFLOAD", "1")
    offload.stop()
    fb0 = M.udf_offload.get(outcome="fallback_transport")
    assert sess.execute(q).rows() == local
    assert M.udf_offload.get(outcome="fallback_transport") > fb0


@pytest.mark.chaos
def test_offload_fault_injected_drop_and_breaker(sess, monkeypatch):
    """utils/fault.py `udf.remote` site: injected transport loss falls
    back locally; repeated losses open the breaker, after which the
    fallback is immediate (BreakerOpen, no dial)."""
    from matrixone_tpu.cluster import rpc as _rpc
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.utils.fault import INJECTOR
    addr = "127.0.0.1:1"          # never dialed: the fault fires first
    monkeypatch.setenv("MO_UDF_OFFLOAD", "1")
    monkeypatch.setenv("MO_UDF_WORKER", addr)
    _mk(sess, name="cf", body="x + 1.0", args="(x DOUBLE)")
    q = "select cf(b) from t where a = 1"
    INJECTOR.add("udf.remote", "return", "drop")
    try:
        fb0 = M.udf_offload.get(outcome="fallback_transport")
        for _ in range(6):        # breaker threshold is 5 failures
            assert sess.execute(q).rows() == [(2.5,)]
        assert M.udf_offload.get(outcome="fallback_transport") > fb0
        assert _rpc.breaker_for(addr).state == "open"
        b0 = M.udf_offload.get(outcome="fallback_breaker")
        assert sess.execute(q).rows() == [(2.5,)]
        assert M.udf_offload.get(outcome="fallback_breaker") > b0
    finally:
        INJECTOR.remove("udf.remote")


@pytest.mark.chaos
def test_worker_error_taxonomy(sess, monkeypatch):
    """Worker error frames keep their taxonomy at the executor: an
    internal worker failure is TRANSIENT (local fallback serves the
    query), only a genuine body error (UdfError) is deterministic and
    surfaces without fallback."""
    from matrixone_tpu.utils import metrics as M
    from matrixone_tpu.worker.client import WorkerClient
    monkeypatch.setenv("MO_UDF_OFFLOAD", "1")
    monkeypatch.setenv("MO_UDF_WORKER", "127.0.0.1:2")   # never dialed
    _mk(sess, name="wf", body="x + 1.0", args="(x DOUBLE)")

    def boom(self, *a, **k):
        raise RuntimeError("worker: MemoryError: exhausted")
    monkeypatch.setattr(WorkerClient, "udf_eval", boom)
    fb0 = M.udf_offload.get(outcome="fallback_transport")
    assert sess.execute(
        "select wf(b) from t where a = 1").rows() == [(2.5,)]
    assert M.udf_offload.get(outcome="fallback_transport") > fb0

    def saysno(self, *a, **k):
        raise RuntimeError("worker: UdfError: udf 'wf': nope")
    monkeypatch.setattr(WorkerClient, "udf_eval", saysno)
    with pytest.raises(ValueError, match="nope"):
        sess.execute("select wf(b) from t where a = 2")


def test_worker_udf_microbatch_coalesces(offload):
    """Concurrent same-signature remote UDF calls coalesce into fewer
    jitted dispatches (the cuvs dynamic-batching pattern on the
    Python-UDF-worker seam)."""
    import threading

    from matrixone_tpu.container import dtypes as dt
    from matrixone_tpu.udf.catalog import UdfMeta
    from matrixone_tpu.worker import WorkerClient
    u = UdfMeta("mb", "scalar", ["x"], [dt.FLOAT64], dt.FLOAT64,
                "python", "x * 3.0", True, True)
    client = WorkerClient(f"127.0.0.1:{offload.port}")
    h0 = client.health()
    barrier = threading.Barrier(16)
    results = [None] * 16

    def one(i):
        xs = np.full(8, float(i))
        barrier.wait()
        out, val, _tier = client.udf_eval(u, [xs],
                                          np.ones(8, np.bool_))
        results[i] = out

    ts = [threading.Thread(target=one, args=(i,)) for i in range(16)]
    for th in ts:
        th.start()
    for th in ts:
        th.join(timeout=60)
    for i in range(16):
        np.testing.assert_allclose(results[i], np.full(8, i * 3.0))
    h1 = client.health()
    reqs = h1["udf_batch_requests"] - h0["udf_batch_requests"]
    disp = h1["udf_batch_dispatches"] - h0["udf_batch_dispatches"]
    assert reqs == 16
    assert disp <= reqs * 0.75, (reqs, disp)   # coalescing happened
    client.close()


# ---------------------------------------------------------- ops surface

def test_mo_ctl_udf_status_and_clear(sess):
    from matrixone_tpu.udf.executor import COMPILE_CACHE
    _mk(sess, name="mf", body="x * 2.0", args="(x DOUBLE)")
    sess.execute("select mf(b) from t")
    st = json.loads(sess.execute(
        "select mo_ctl('udf','status')").rows()[0][0])
    assert st["functions"] >= 1
    assert st["compile_cache"]["entries"] >= 1
    sess.execute("select mo_ctl('udf','clear')")
    assert COMPILE_CACHE.stats()["entries"] == 0


def test_explain_analyze_reports_udf_rows(sess):
    _mk(sess, name="ef", body="x + 0.0", args="(x DOUBLE)")
    txt = sess.execute("explain analyze select ef(b) from t").text
    line = [ln for ln in txt.splitlines() if "UdfCall ef" in ln
            and "rows=" in ln]
    assert line, txt
    assert "rows=4" in line[0]

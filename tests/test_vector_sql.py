"""Vector search through SQL: CREATE INDEX + ORDER BY distance LIMIT k
(reference analogue: test/distributed/cases/vector BVT cases)."""

import numpy as np
import pytest

from matrixone_tpu.frontend import Session


@pytest.fixture(scope="module")
def vsess():
    s = Session()
    s.execute("create table items (id bigint primary key, emb vecf32(16))")
    rng = np.random.default_rng(11)
    centers = rng.standard_normal((8, 16)) * 4
    rows = []
    for i in range(2000):
        c = centers[i % 8]
        v = c + rng.standard_normal(16) * 0.3
        vec = "[" + ",".join(f"{x:.4f}" for x in v) + "]"
        rows.append(f"({i}, '{vec}')")
    for j in range(0, 2000, 500):
        s.execute("insert into items values " + ", ".join(rows[j:j + 500]))
    s.execute("create index iv using ivfflat on items (emb) "
              "lists = 16 op_type = 'vector_l2_ops'")
    return s, centers


def _knn_sql(center):
    vec = "[" + ",".join(f"{x:.4f}" for x in center) + "]"
    return (f"select id, l2_distance(emb, '{vec}') d from items "
            f"order by d limit 10")


def test_index_rewrite_in_plan(vsess):
    s, centers = vsess
    txt = s.execute("explain " + _knn_sql(centers[0])).text
    # EXPLAIN shows the pre-rewrite plan (rewrite applies at execution);
    # check the rewrite directly
    from matrixone_tpu.sql.binder import Binder
    from matrixone_tpu.sql.optimize import apply_indices
    from matrixone_tpu.sql.parser import parse_one
    from matrixone_tpu.sql import plan as P
    node = Binder(s.catalog).bind_select(parse_one(_knn_sql(centers[0])))
    node = apply_indices(node, s.catalog)
    found = []

    def walk(n):
        found.append(type(n).__name__)
        for a in ("child", "left", "right"):
            c = getattr(n, a, None)
            if c is not None:
                walk(c)
    walk(node)
    assert "VectorTopK" in found and "Scan" not in found


def test_knn_results_match_exact(vsess):
    s, centers = vsess
    for ci in range(4):
        rows = s.execute(_knn_sql(centers[ci])).rows()
        assert len(rows) == 10
        # distances ascending
        ds = [r[1] for r in rows]
        assert ds == sorted(ds)
        # oracle: brute force over raw vectors via SQL w/o index
        # (drop index path by using a fresh session w/o indexes)
        import copy
        from matrixone_tpu.sql.binder import Binder
        from matrixone_tpu.sql.parser import parse_one
        from matrixone_tpu.vm.compile import compile_plan
        node = Binder(s.catalog).bind_select(parse_one(_knn_sql(centers[ci])))
        op = compile_plan(node, s.catalog)  # no apply_indices -> full scan
        exact_rows = []
        for ex in op.execute():
            b = s._to_host(ex, node.schema)
            ids = b.columns["id"].to_pylist()
            dd = b.columns["d"].to_pylist()
            exact_rows = list(zip(ids, dd))
        exact_ids = {r[0] for r in exact_rows}
        got_ids = {r[0] for r in rows}
        # IVF recall at nprobe=8/16 lists on well-separated clusters
        assert len(got_ids & exact_ids) >= 8


def test_knn_excludes_deleted(vsess):
    s, centers = vsess
    rows = s.execute(_knn_sql(centers[1])).rows()
    victim = rows[0][0]
    s.execute(f"delete from items where id = {victim}")
    rows2 = s.execute(_knn_sql(centers[1])).rows()
    assert victim not in {r[0] for r in rows2}
    # restore-ish: further queries still work
    assert len(rows2) == 10


def test_cosine_index():
    s = Session()
    s.execute("create table docs (id bigint, emb vecf32(8))")
    rng = np.random.default_rng(3)
    vals = rng.standard_normal((200, 8))
    for i in range(200):
        vec = "[" + ",".join(f"{x:.4f}" for x in vals[i]) + "]"
        s.execute(f"insert into docs values ({i}, '{vec}')")
    s.execute("create index cv using ivfflat on docs (emb) "
              "lists = 4 op_type = 'vector_cosine_ops'")
    q = vals[7]
    vec = "[" + ",".join(f"{x:.4f}" for x in q) + "]"
    rows = s.execute(f"select id, cosine_distance(emb, '{vec}') d from docs "
                     f"order by d limit 3").rows()
    assert rows[0][0] == 7 and rows[0][1] < 1e-6


def test_hnsw_sql_index():
    s = Session()
    s.execute("create table hx (id bigint, e vecf32(16))")
    rng = np.random.default_rng(8)
    vals = rng.standard_normal((500, 16)).astype(np.float32)
    buf = []
    for i in range(500):
        buf.append(f"({i}, '[{','.join(f'{x:.4f}' for x in vals[i])}]')")
    s.execute("insert into hx values " + ",".join(buf))
    s.execute("create index hn using hnsw on hx (e) m = 12 ef_construction = 48")
    q = vals[42]
    vec = "[" + ",".join(f"{x:.4f}" for x in q) + "]"
    rows = s.execute(f"select id from hx order by l2_distance(e, '{vec}') limit 3").rows()
    assert rows[0][0] == 42
    # rewrite actually used
    from matrixone_tpu.sql import plan as P
    txt = s.execute(f"explain select id from hx order by l2_distance(e, '{vec}') limit 3").text
    assert "VectorTopK" in txt and "hn" in txt
    # stays correct after dml (lazy rebuild)
    s.execute("delete from hx where id = 42")
    rows = s.execute(f"select id from hx order by l2_distance(e, '{vec}') limit 3").rows()
    assert 42 not in [r[0] for r in rows]

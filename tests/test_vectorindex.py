"""IVF-Flat / k-means / brute force on small data (CPU mesh), recall checks."""

import jax.numpy as jnp
import numpy as np

from matrixone_tpu.vectorindex import brute_force, ivf_flat, kmeans
from matrixone_tpu.vectorindex.recall import recall_at_k


def _clustered_data(rng, n=20000, d=32, n_clusters=50):
    centers = rng.standard_normal((n_clusters, d)) * 5
    labels = rng.integers(0, n_clusters, n)
    return (centers[labels] + rng.standard_normal((n, d))).astype(np.float32)


def test_brute_force_exact(rng):
    x = rng.standard_normal((5000, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=1024)
    scores, idx = brute_force.search(padded, jnp.asarray(q), k=10,
                                     n_valid=n, chunk_size=1024)
    oracle = np.argsort(((x[:, None].astype(np.float64)
                          - q[None].astype(np.float64)) ** 2).sum(-1), axis=0)[:10].T
    assert recall_at_k(np.asarray(idx), oracle) == 1.0
    assert np.asarray(idx).max() < n  # padding never returned


def test_kmeans_clusters(rng):
    x = _clustered_data(rng)
    km = kmeans.fit(jnp.asarray(x), 50, n_iter=8, sample=None)
    assert int(km.cluster_sizes.sum()) == len(x)
    # every point's centroid is closer than a random centroid on average
    c = np.asarray(km.centroids)
    lab = np.asarray(km.labels)
    own = np.linalg.norm(x - c[lab], axis=1).mean()
    rnd = np.linalg.norm(x - c[(lab + 7) % 50], axis=1).mean()
    assert own < rnd * 0.6


def test_kmeans_balance(rng):
    x = _clustered_data(rng, n=10000)
    km_bal = kmeans.fit(jnp.asarray(x), 32, n_iter=10, balance_weight=0.5,
                        sample=None)
    sizes = np.asarray(km_bal.cluster_sizes)
    assert sizes.max() <= sizes.mean() * 4  # no degenerate mega-cluster


def test_ivf_flat_recall_and_structure():
    rng = np.random.default_rng(55)
    x = _clustered_data(rng, n=20000, d=32)
    q = x[rng.integers(0, len(x), 32)] + 0.01 * rng.standard_normal((32, 32)).astype(np.float32)
    q = q.astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=64, n_iter=8,
                           kmeans_sample=None, compute_dtype=None)
    # CSR structure invariants
    offs = np.asarray(index.offsets)
    assert offs[0] == 0 and offs[-1] == len(x)
    assert (np.diff(offs) >= 0).all()
    assert (np.diff(offs).max()) <= index.max_cluster_size
    assert sorted(np.asarray(index.ids).tolist()) == list(range(len(x)))

    dist, ids = ivf_flat.search(index, jnp.asarray(q), k=10, nprobe=8,
                                query_chunk=16, compute_dtype=jnp.float32)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=4096)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=4096)
    r = recall_at_k(np.asarray(ids), np.asarray(truth))
    assert r >= 0.9, r
    # distances must be sorted ascending per query
    dd = np.asarray(dist)
    assert (np.diff(dd, axis=1) >= -1e-5).all()


def test_ivf_cosine_metric():
    rng = np.random.default_rng(56)
    x = rng.standard_normal((8000, 24)).astype(np.float32)
    q = rng.standard_normal((16, 24)).astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=32, metric="cosine",
                           n_iter=8, kmeans_sample=None, compute_dtype=None)
    dist, ids = ivf_flat.search(index, jnp.asarray(q), k=5, nprobe=16,
                                query_chunk=16, compute_dtype=jnp.float32)
    # oracle cosine
    xn = x / np.linalg.norm(x, axis=1, keepdims=True)
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    truth = np.argsort(1 - xn @ qn.T, axis=0)[:5].T
    assert recall_at_k(np.asarray(ids), truth) >= 0.85


def test_rerank_exact_orders_bit_identically(rng):
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((4, 16)).astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=16, n_iter=5,
                           kmeans_sample=None, compute_dtype=None)
    _, ids = ivf_flat.search(index, jnp.asarray(q), k=10, nprobe=16,
                             query_chunk=4, compute_dtype=jnp.float32)
    dist, ids2 = ivf_flat.rerank_exact(jnp.asarray(x), jnp.asarray(q), ids)
    # oracle: same sequential f64 fold on host
    for i in range(4):
        cand = x[np.asarray(ids)[i]].astype(np.float64)
        sq = (cand - q[i].astype(np.float64)) ** 2
        acc = np.zeros(len(cand))
        for j in range(sq.shape[1]):
            acc = acc + sq[:, j]
        exp = np.sqrt(acc)
        order = np.argsort(exp)
        np.testing.assert_array_equal(np.asarray(ids2)[i], np.asarray(ids)[i][order])
        np.testing.assert_array_equal(np.asarray(dist)[i], exp[order])


def test_ivf_pq_recall_and_memory():
    # own fixed rng: the shared session fixture makes data depend on test
    # execution order, and PQ recall thresholds are draw-sensitive
    rng = np.random.default_rng(1234)
    from matrixone_tpu.vectorindex import ivf_pq
    x = _clustered_data(rng, n=20000, d=32)
    q = (x[rng.integers(0, len(x), 32)]
         + 0.01 * rng.standard_normal((32, 32))).astype(np.float32)
    index = ivf_pq.build(jnp.asarray(x), nlist=32, n_subspaces=8,
                         n_iter=8, pq_iter=6, kmeans_sample=None,
                         compute_dtype=None)
    # 8 bytes/vector instead of 128 (f32 flat)
    assert index.codes.dtype == jnp.uint8
    assert index.codes.shape == (len(x), 8)
    dist, ids = ivf_pq.search(index, jnp.asarray(q), k=10, nprobe=8,
                              query_chunk=16)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=4096)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=4096)
    r = recall_at_k(np.asarray(ids), np.asarray(truth))
    assert r >= 0.4, r        # raw ADC: PQ trades recall for 16x memory
    # exact re-rank over a deeper candidate pool recovers recall (this is
    # what the SQL path's overfetch+Project-recompute does)
    _, ids50 = ivf_pq.search(index, jnp.asarray(q), k=50, nprobe=8,
                             query_chunk=16)
    _, rr = ivf_flat.rerank_exact(jnp.asarray(x), jnp.asarray(q),
                                  ids50)
    r2 = recall_at_k(np.asarray(rr)[:, :10], np.asarray(truth))
    assert r2 >= 0.8, (r, r2)


def test_hnsw_recall():
    rng = np.random.default_rng(77)
    from matrixone_tpu.vectorindex import hnsw
    x = _clustered_data(rng, n=3000, d=24)
    q = (x[rng.integers(0, len(x), 16)]
         + 0.01 * rng.standard_normal((16, 24))).astype(np.float32)
    index = hnsw.build(x, M=12, ef_construction=48)
    d, ids = hnsw.search(index, q, k=10, ef=64)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=1024)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=1024)
    r = recall_at_k(ids, np.asarray(truth))
    assert r >= 0.9, r
    # distances ascending, self-hit first
    assert (np.diff(d, axis=1) >= -1e-5).all()
    np.testing.assert_array_equal(
        ids[:, 0], np.asarray(truth)[:, 0])


def test_hnsw_cosine():
    rng = np.random.default_rng(78)
    from matrixone_tpu.vectorindex import hnsw
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = x[:4] * 2.5           # scaled copies: cosine-nearest = themselves
    index = hnsw.build(x, M=12, metric="cosine")
    _, ids = hnsw.search(index, q, k=3, ef=48)
    np.testing.assert_array_equal(ids[:, 0], np.arange(4))


def test_hnsw_native_walker_matches_python_oracle():
    """VERDICT r1 Weak #4: the C++ graph walker (usearch role) must match
    the pure-Python oracle's recall on clustered data."""
    from matrixone_tpu.vectorindex import hnsw
    from matrixone_tpu.vectorindex.recall import recall_at_k
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(16, 24)).astype(np.float32)
    lab = rng.integers(0, 16, 4000)
    data = centers[lab] + rng.normal(size=(4000, 24)).astype(np.float32) * 0.15
    q = centers[rng.integers(0, 16, 64)] + \
        rng.normal(size=(64, 24)).astype(np.float32) * 0.15

    nat = hnsw.build(data, M=12, ef_construction=64)
    assert isinstance(nat, hnsw.NativeHnswIndex), "native lib must load"
    py = hnsw.build(data, M=12, ef_construction=64, native=False)

    # exact ground truth
    d2 = ((data[None, :, :] - q[:, None, :]) ** 2).sum(-1)
    truth = np.argsort(d2, axis=1)[:, :10]
    _, ids_n = hnsw.search(nat, q, k=10, ef=96)
    _, ids_p = hnsw.search(py, q, k=10, ef=96)
    r_nat = recall_at_k(ids_n, truth)
    r_py = recall_at_k(ids_p, truth)
    assert r_nat >= 0.9, r_nat
    assert r_nat >= r_py - 0.05, (r_nat, r_py)

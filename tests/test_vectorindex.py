"""IVF-Flat / k-means / brute force on small data (CPU mesh), recall checks."""

import jax.numpy as jnp
import numpy as np

from matrixone_tpu.vectorindex import brute_force, ivf_flat, kmeans
from matrixone_tpu.vectorindex.recall import recall_at_k


def _clustered_data(rng, n=20000, d=32, n_clusters=50):
    centers = rng.standard_normal((n_clusters, d)) * 5
    labels = rng.integers(0, n_clusters, n)
    return (centers[labels] + rng.standard_normal((n, d))).astype(np.float32)


def test_brute_force_exact(rng):
    x = rng.standard_normal((5000, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=1024)
    scores, idx = brute_force.search(padded, jnp.asarray(q), k=10,
                                     n_valid=n, chunk_size=1024)
    oracle = np.argsort(((x[:, None].astype(np.float64)
                          - q[None].astype(np.float64)) ** 2).sum(-1), axis=0)[:10].T
    assert recall_at_k(np.asarray(idx), oracle) == 1.0
    assert np.asarray(idx).max() < n  # padding never returned


def test_kmeans_clusters(rng):
    x = _clustered_data(rng)
    km = kmeans.fit(jnp.asarray(x), 50, n_iter=8, sample=None)
    assert int(km.cluster_sizes.sum()) == len(x)
    # every point's centroid is closer than a random centroid on average
    c = np.asarray(km.centroids)
    lab = np.asarray(km.labels)
    own = np.linalg.norm(x - c[lab], axis=1).mean()
    rnd = np.linalg.norm(x - c[(lab + 7) % 50], axis=1).mean()
    assert own < rnd * 0.6


def test_kmeans_balance(rng):
    x = _clustered_data(rng, n=10000)
    km_bal = kmeans.fit(jnp.asarray(x), 32, n_iter=10, balance_weight=0.5,
                        sample=None)
    sizes = np.asarray(km_bal.cluster_sizes)
    assert sizes.max() <= sizes.mean() * 4  # no degenerate mega-cluster


def test_ivf_flat_recall_and_structure():
    rng = np.random.default_rng(55)
    x = _clustered_data(rng, n=20000, d=32)
    q = x[rng.integers(0, len(x), 32)] + 0.01 * rng.standard_normal((32, 32)).astype(np.float32)
    q = q.astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=64, n_iter=8,
                           kmeans_sample=None, compute_dtype=None)
    # CSR structure invariants
    offs = np.asarray(index.offsets)
    assert offs[0] == 0 and offs[-1] == len(x)
    assert (np.diff(offs) >= 0).all()
    assert (np.diff(offs).max()) <= index.max_cluster_size
    assert sorted(np.asarray(index.ids).tolist()) == list(range(len(x)))

    dist, ids = ivf_flat.search(index, jnp.asarray(q), k=10, nprobe=8,
                                query_chunk=16, compute_dtype=jnp.float32)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=4096)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=4096)
    r = recall_at_k(np.asarray(ids), np.asarray(truth))
    assert r >= 0.9, r
    # distances must be sorted ascending per query
    dd = np.asarray(dist)
    assert (np.diff(dd, axis=1) >= -1e-5).all()


def test_ivf_cosine_metric():
    rng = np.random.default_rng(56)
    x = rng.standard_normal((8000, 24)).astype(np.float32)
    q = rng.standard_normal((16, 24)).astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=32, metric="cosine",
                           n_iter=8, kmeans_sample=None, compute_dtype=None)
    dist, ids = ivf_flat.search(index, jnp.asarray(q), k=5, nprobe=16,
                                query_chunk=16, compute_dtype=jnp.float32)
    # oracle cosine
    xn = x / np.linalg.norm(x, axis=1, keepdims=True)
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    truth = np.argsort(1 - xn @ qn.T, axis=0)[:5].T
    assert recall_at_k(np.asarray(ids), truth) >= 0.85


def test_rerank_exact_orders_bit_identically(rng):
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((4, 16)).astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=16, n_iter=5,
                           kmeans_sample=None, compute_dtype=None)
    _, ids = ivf_flat.search(index, jnp.asarray(q), k=10, nprobe=16,
                             query_chunk=4, compute_dtype=jnp.float32)
    dist, ids2 = ivf_flat.rerank_exact(jnp.asarray(x), jnp.asarray(q), ids)
    # oracle: same sequential f64 fold on host
    for i in range(4):
        cand = x[np.asarray(ids)[i]].astype(np.float64)
        sq = (cand - q[i].astype(np.float64)) ** 2
        acc = np.zeros(len(cand))
        for j in range(sq.shape[1]):
            acc = acc + sq[:, j]
        exp = np.sqrt(acc)
        order = np.argsort(exp)
        np.testing.assert_array_equal(np.asarray(ids2)[i], np.asarray(ids)[i][order])
        np.testing.assert_array_equal(np.asarray(dist)[i], exp[order])


def test_search_pads_any_batch_size():
    """Callers no longer pad to query_chunk: odd batch sizes are padded
    internally (power-of-two bucketing) and pad rows never leak into or
    perturb real rows' results. The comparison runs both sides at the
    SAME compiled shape (37 padded to 64 internally vs an explicit
    zero-padded 64 batch), so equality is bit-exact — cross-shape runs
    can legitimately differ in the last ulp on near-ties."""
    rng = np.random.default_rng(91)
    x = _clustered_data(rng, n=8000, d=16)
    q = x[rng.integers(0, len(x), 37)].astype(np.float32)
    index = ivf_flat.build(jnp.asarray(x), nlist=32, n_iter=6,
                           kmeans_sample=None, compute_dtype=None)
    d_a, i_a = ivf_flat.search(index, jnp.asarray(q), k=5, nprobe=8,
                               compute_dtype=jnp.float32)
    assert i_a.shape == (37, 5)
    q64 = np.concatenate([q, np.zeros((27, 16), np.float32)])
    d_b, i_b = ivf_flat.search(index, jnp.asarray(q64), k=5, nprobe=8,
                               compute_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(i_a), np.asarray(i_b)[:37])
    np.testing.assert_array_equal(np.asarray(d_a), np.asarray(d_b)[:37])


def test_kmeans_single_compile(rng):
    """The Lloyd loop must be ONE compiled program: the balance-weight
    schedule is traced, so flipping balancing on mid-fit (the late-iter
    schedule) cannot trigger a second XLA compile. Guard via the jit
    cache-miss counter (_cache_size)."""
    x = _clustered_data(rng, n=6000, d=16)
    before = kmeans._lloyd_loop._cache_size()
    kmeans.fit(jnp.asarray(x), 32, n_iter=6, balance_weight=0.4,
               sample=None)
    after_one = kmeans._lloyd_loop._cache_size()
    # second fit, same shapes, different weights/seed: zero new compiles
    kmeans.fit(jnp.asarray(x), 32, n_iter=6, balance_weight=0.0, seed=3,
               sample=None)
    after_two = kmeans._lloyd_loop._cache_size()
    assert after_one - before == 1, (before, after_one)
    assert after_two == after_one, (after_one, after_two)


def test_split_balance_build():
    """balance_mode='split' bounds every inverted list by local cluster
    splitting instead of cross-cluster relocation: the padded gather
    budget shrinks while recall does NOT regress vs the capped build.
    Own fixed rng: the shared session fixture makes data depend on test
    order and this guards an absolute recall floor."""
    rng = np.random.default_rng(4242)
    x = _clustered_data(rng, n=16000, d=32, n_clusters=40)
    q = (x[rng.integers(0, len(x), 48)]
         + 0.01 * rng.standard_normal((48, 32))).astype(np.float32)
    kw = dict(nlist=64, n_iter=6, kmeans_sample=None,
              compute_dtype=None)
    cap = ivf_flat.build(jnp.asarray(x), **kw)
    split = ivf_flat.build(jnp.asarray(x), balance_mode="split",
                           target_list_size=224, **kw)
    assert split.max_cluster_size <= cap.max_cluster_size
    offs = np.asarray(split.offsets)
    assert offs[-1] == len(x)
    assert sorted(np.asarray(split.ids).tolist()) == list(range(len(x)))
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=4096)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=20, n_valid=n,
                                  chunk_size=4096)
    r_cap, r_split = [
        recall_at_k(np.asarray(ivf_flat.search(
            ix, jnp.asarray(q), k=20, nprobe=8,
            compute_dtype=jnp.float32)[1]), np.asarray(truth))
        for ix in (cap, split)]
    assert r_split >= 0.86, r_split        # the bench acceptance guard
    assert r_split >= r_cap - 0.02, (r_split, r_cap)


def test_ivf_pq_recall_and_memory():
    # own fixed rng: the shared session fixture makes data depend on test
    # execution order, and PQ recall thresholds are draw-sensitive
    rng = np.random.default_rng(1234)
    from matrixone_tpu.vectorindex import ivf_pq
    x = _clustered_data(rng, n=20000, d=32)
    q = (x[rng.integers(0, len(x), 32)]
         + 0.01 * rng.standard_normal((32, 32))).astype(np.float32)
    index = ivf_pq.build(jnp.asarray(x), nlist=32, n_subspaces=8,
                         n_iter=8, pq_iter=6, kmeans_sample=None,
                         compute_dtype=None)
    # 8 bytes/vector instead of 128 (f32 flat)
    assert index.codes.dtype == jnp.uint8
    assert index.codes.shape == (len(x), 8)
    dist, ids = ivf_pq.search(index, jnp.asarray(q), k=10, nprobe=8,
                              query_chunk=16)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=4096)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=4096)
    r = recall_at_k(np.asarray(ids), np.asarray(truth))
    assert r >= 0.4, r        # raw ADC: PQ trades recall for 16x memory
    # exact re-rank over a deeper candidate pool recovers recall (this is
    # what the SQL path's overfetch+Project-recompute does). Pool 100 at
    # n=20000: pool 50 sat within ~2pp of the threshold and flapped with
    # the k-means fp ordering (draw-sensitive, per the fixture note)
    _, ids100 = ivf_pq.search(index, jnp.asarray(q), k=100, nprobe=8,
                              query_chunk=16)
    _, rr = ivf_flat.rerank_exact(jnp.asarray(x), jnp.asarray(q),
                                  ids100)
    r2 = recall_at_k(np.asarray(rr)[:, :10], np.asarray(truth))
    assert r2 >= 0.85, (r, r2)


def test_hnsw_recall():
    rng = np.random.default_rng(77)
    from matrixone_tpu.vectorindex import hnsw
    x = _clustered_data(rng, n=3000, d=24)
    q = (x[rng.integers(0, len(x), 16)]
         + 0.01 * rng.standard_normal((16, 24))).astype(np.float32)
    index = hnsw.build(x, M=12, ef_construction=48)
    d, ids = hnsw.search(index, q, k=10, ef=64)
    padded, n = brute_force.pad_dataset(jnp.asarray(x), chunk_size=1024)
    _, truth = brute_force.search(padded, jnp.asarray(q), k=10, n_valid=n,
                                  chunk_size=1024)
    r = recall_at_k(ids, np.asarray(truth))
    assert r >= 0.9, r
    # distances ascending, self-hit first
    assert (np.diff(d, axis=1) >= -1e-5).all()
    np.testing.assert_array_equal(
        ids[:, 0], np.asarray(truth)[:, 0])


def test_hnsw_cosine():
    rng = np.random.default_rng(78)
    from matrixone_tpu.vectorindex import hnsw
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = x[:4] * 2.5           # scaled copies: cosine-nearest = themselves
    index = hnsw.build(x, M=12, metric="cosine")
    _, ids = hnsw.search(index, q, k=3, ef=48)
    np.testing.assert_array_equal(ids[:, 0], np.arange(4))


def test_hnsw_native_walker_matches_python_oracle():
    """VERDICT r1 Weak #4: the C++ graph walker (usearch role) must match
    the pure-Python oracle's recall on clustered data."""
    from matrixone_tpu.vectorindex import hnsw
    from matrixone_tpu.vectorindex.recall import recall_at_k
    # 1400 pts, not 4000: the pure-python oracle build is O(n*ef*M) and
    # was alone ~50s of every tier-1 run — the native-vs-oracle recall
    # comparison this guards is just as discriminating at this size
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(16, 24)).astype(np.float32)
    lab = rng.integers(0, 16, 1400)
    data = centers[lab] + rng.normal(size=(1400, 24)).astype(np.float32) * 0.15
    q = centers[rng.integers(0, 16, 64)] + \
        rng.normal(size=(64, 24)).astype(np.float32) * 0.15

    nat = hnsw.build(data, M=12, ef_construction=64)
    assert isinstance(nat, hnsw.NativeHnswIndex), "native lib must load"
    py = hnsw.build(data, M=12, ef_construction=64, native=False)

    # exact ground truth
    d2 = ((data[None, :, :] - q[:, None, :]) ** 2).sum(-1)
    truth = np.argsort(d2, axis=1)[:, :10]
    _, ids_n = hnsw.search(nat, q, k=10, ef=96)
    _, ids_p = hnsw.search(py, q, k=10, ef=96)
    r_nat = recall_at_k(ids_n, truth)
    r_py = recall_at_k(ids_p, truth)
    assert r_nat >= 0.9, r_nat
    assert r_nat >= r_py - 0.05, (r_nat, r_py)

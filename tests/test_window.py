"""Window functions vs pandas oracle (reference: colexec/window BVT)."""

import numpy as np
import pandas as pd
import pytest

from matrixone_tpu.frontend import Session


@pytest.fixture(scope="module")
def wsess(rng=np.random.default_rng(13)):
    s = Session()
    s.execute("create table t (g varchar(2), v bigint, p decimal(8,2))")
    g = rng.choice(list("abcd"), 200)
    v = rng.integers(0, 40, 200)   # plenty of ties
    p = np.round(rng.uniform(0, 100, 200), 2)
    rows = ", ".join(f"('{g[i]}', {v[i]}, {p[i]})" for i in range(200))
    s.execute("insert into t values " + rows)
    df = pd.DataFrame({"g": g, "v": v, "p": p})
    return s, df


def _sorted_rows(rows):
    return sorted(rows)


def test_ranking_functions(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        row_number() over (partition by g order by v) rn,
        rank() over (partition by g order by v) rk,
        dense_rank() over (partition by g order by v) dr
        from t order by g, v, rn""").rows()
    d = df.sort_values(["g", "v"]).copy()
    d["rn"] = d.groupby("g").cumcount() + 1
    d["rk"] = d.groupby("g")["v"].rank(method="min").astype(int)
    d["dr"] = d.groupby("g")["v"].rank(method="dense").astype(int)
    exp = list(d[["g", "v", "rn", "rk", "dr"]].itertuples(index=False,
                                                          name=None))
    assert got == exp


def test_cumulative_sum_range_peers(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        sum(v) over (partition by g order by v) cs
        from t order by g, v""").rows()
    d = df.sort_values(["g", "v"]).copy()
    # RANGE frame: peers share the cumulative value of the last peer
    d["cs"] = d.groupby("g")["v"].cumsum()
    d["cs"] = d.groupby(["g", "v"])["cs"].transform("max")
    exp = list(d[["g", "v", "cs"]].itertuples(index=False, name=None))
    assert sorted(got) == sorted(exp)


def test_partition_totals_and_counts(wsess):
    s, df = wsess
    got = s.execute("""select g, sum(v) over (partition by g) t,
        count(*) over (partition by g) c from t order by g limit 4""").rows()
    sums = df.groupby("g")["v"].sum()
    counts = df.groupby("g")["v"].count()
    for g_, t_, c_ in got:
        assert t_ == sums[g_] and c_ == counts[g_]


def test_running_min_max_and_avg(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        min(v) over (partition by g order by v) mn,
        max(v) over (partition by g order by v) mx,
        avg(v) over (partition by g order by v) av
        from t order by g, v""").rows()
    d = df.sort_values(["g", "v"]).copy()
    d["mn"] = d.groupby("g")["v"].cummin()
    d["mx"] = d.groupby("g")["v"].cummax()
    d["cs"] = d.groupby("g")["v"].cumsum()
    d["cn"] = d.groupby("g").cumcount() + 1
    d["av"] = d["cs"] / d["cn"]
    for c in ("mn", "mx", "av"):
        d[c] = d.groupby(["g", "v"])[c].transform(
            "max" if c != "mn" else "min")
    # avg peers share last-peer value
    d["av"] = d.groupby(["g", "v"])["cs"].transform("max") / \
        d.groupby(["g", "v"])["cn"].transform("max")
    exp = {(r[0], r[1]): (r[2], r[3], round(r[4], 9))
           for r in d[["g", "v", "mn", "mx", "av"]].itertuples(
               index=False, name=None)}
    for g_, v_, mn, mx, av in got:
        emn, emx, eav = exp[(g_, v_)]
        assert mn == emn and mx == emx and abs(av - eav) < 1e-9


def test_window_without_partition(wsess):
    s, df = wsess
    got = s.execute("select v, row_number() over (order by v) rn "
                    "from t order by v, rn limit 3").rows()
    assert [r[1] for r in got] == [1, 2, 3]


def test_window_error_paths(wsess):
    s, _ = wsess
    with pytest.raises(Exception, match="not a window function"):
        s.execute("select upper(g) over (partition by g) from t")
    with pytest.raises(Exception, match="top-level"):
        s.execute("select 1 + row_number() over (order by v) from t")


def test_window_all_null_frame_yields_null():
    s = Session()
    s.execute("create table n (g varchar(2), v bigint)")
    s.execute("insert into n values ('a', null), ('a', null), ('b', 1)")
    rows = s.execute("""select g, sum(v) over (partition by g) sv,
        min(v) over (partition by g) mv from n order by g""").rows()
    assert rows[0] == ("a", None, None)
    assert rows[2] == ("b", 1, 1)


def test_window_over_group_by():
    s = Session()
    s.execute("create table t (g varchar(2), v bigint)")
    s.execute("insert into t values ('a',1),('a',2),('b',10),('b',20),('c',3)")
    rows = s.execute("""select g, sum(v) s,
        rank() over (order by sum(v) desc) rk
        from t group by g order by rk""").rows()
    assert rows == [("b", 30, 1), ("c", 3, 2), ("a", 3, 2)] or \
           rows == [("b", 30, 1), ("a", 3, 2), ("c", 3, 2)]


def test_window_invalid_forms():
    s = Session()
    s.execute("create table t (g varchar(2), v bigint)")
    s.execute("insert into t values ('a', 1)")
    with pytest.raises(Exception, match=r"sum\(\*\)"):
        s.execute("select sum(*) over (partition by g) from t")
    with pytest.raises(Exception, match="DISTINCT"):
        s.execute("select count(distinct v) over (partition by g) from t")
    with pytest.raises(Exception, match="strings"):
        s.execute("select min(g) over (partition by v) from t")


# ------------------------------------- r5: value functions + ROWS frames
def _win_fixture():
    s = Session()
    s.execute("create table w (id bigint primary key, g bigint,"
              " v bigint, nm varchar(8))")
    rows = [(1, 1, 10, 'a'), (2, 1, 30, 'b'), (3, 1, 20, 'c'),
            (4, 2, 5, 'd'), (5, 2, 15, 'e'), (6, 3, 7, 'f')]
    s.execute("insert into w values " +
              ",".join(f"({a},{b},{c},'{d}')" for a, b, c, d in rows))
    return s


def test_lag_lead():
    s = _win_fixture()
    got = s.execute(
        "select id, lag(v) over (partition by g order by id),"
        " lead(v) over (partition by g order by id),"
        " lag(v, 2, -1) over (partition by g order by id)"
        " from w order by id").rows()
    assert got == [(1, None, 30, -1), (2, 10, 20, -1), (3, 30, None, 10),
                   (4, None, 15, -1), (5, 5, None, -1),
                   (6, None, None, -1)]


def test_lag_over_strings():
    s = _win_fixture()
    got = s.execute(
        "select id, lag(nm) over (partition by g order by id)"
        " from w order by id").rows()
    assert got == [(1, None), (2, 'a'), (3, 'b'), (4, None), (5, 'd'),
                   (6, None)]


def test_first_last_nth_value():
    s = _win_fixture()
    got = s.execute(
        "select id, first_value(v) over (partition by g order by v),"
        " last_value(v) over (partition by g order by v"
        "   rows between unbounded preceding and unbounded following),"
        " nth_value(v, 2) over (partition by g order by v)"
        " from w order by id").rows()
    # partition 1 ordered by v: 10,20,30; partition 2: 5,15; part 3: 7
    assert got == [(1, 10, 30, None), (2, 10, 30, 20), (3, 10, 30, 20),
                   (4, 5, 15, None), (5, 5, 15, 15), (6, 7, 7, None)]


def test_ntile():
    s = _win_fixture()
    got = s.execute(
        "select id, ntile(2) over (order by id) from w"
        " order by id").rows()
    # 6 rows, 2 buckets of 3
    assert [r[1] for r in got] == [1, 1, 1, 2, 2, 2]
    got3 = s.execute(
        "select id, ntile(4) over (order by id) from w"
        " order by id").rows()
    # 6 rows, 4 buckets: sizes 2,2,1,1
    assert [r[1] for r in got3] == [1, 1, 2, 2, 3, 4]


def test_rows_frame_sum_avg_count():
    s = _win_fixture()
    got = s.execute(
        "select id, sum(v) over (partition by g order by id"
        "   rows between 1 preceding and current row),"
        " count(*) over (order by id rows between 1 preceding"
        "   and 1 following)"
        " from w order by id").rows()
    assert got == [(1, 10, 2), (2, 40, 3), (3, 50, 3),
                   (4, 5, 3), (5, 20, 3), (6, 7, 2)]


def test_rows_frame_min_max():
    s = _win_fixture()
    got = s.execute(
        "select id, min(v) over (order by id rows between 2 preceding"
        "   and current row),"
        " max(v) over (order by id rows between current row"
        "   and 2 following)"
        " from w order by id").rows()
    # v by id: 10,30,20,5,15,7 (no PARTITION BY: one global partition)
    assert got == [(1, 10, 30), (2, 10, 30), (3, 10, 20),
                   (4, 5, 15), (5, 5, 15), (6, 5, 7)]


def test_rows_frame_vs_pandas_random():
    import pandas as pd
    s = Session()
    s.execute("create table r (id bigint primary key, g bigint,"
              " v double)")
    rng = np.random.default_rng(11)
    n = 500
    gs = rng.integers(0, 7, n)
    vs = np.round(rng.normal(size=n), 6)
    s.execute("insert into r values " +
              ",".join(f"({i},{gs[i]},{vs[i]})" for i in range(n)))
    got = s.execute(
        "select id, sum(v) over (partition by g order by id"
        "   rows between 3 preceding and 1 following),"
        " min(v) over (partition by g order by id"
        "   rows between 2 preceding and 2 following)"
        " from r order by id").rows()
    # python-loop oracle (explicit frame semantics, partition-aware)
    import collections
    by_g = collections.defaultdict(list)
    for i in range(n):
        by_g[gs[i]].append(i)
    exp = {}
    for g, ids in by_g.items():
        for j, i in enumerate(ids):
            w5 = [vs[ids[t]] for t in range(max(0, j - 3),
                                            min(len(ids), j + 2))]
            w_min = [vs[ids[t]] for t in range(max(0, j - 2),
                                               min(len(ids), j + 3))]
            exp[i] = (sum(w5), min(w_min))
    for (i, sm, mn) in got:
        es, em = exp[int(i)]
        assert abs(float(sm) - es) < 1e-9, (i, sm, es)
        assert abs(float(mn) - em) < 1e-12, (i, mn, em)


def test_frame_rejected_for_rank_funcs():
    s = _win_fixture()
    import pytest as _pt
    with _pt.raises(Exception):
        s.execute("select rank() over (order by id rows between"
                  " 1 preceding and current row) from w")

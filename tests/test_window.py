"""Window functions vs pandas oracle (reference: colexec/window BVT)."""

import numpy as np
import pandas as pd
import pytest

from matrixone_tpu.frontend import Session


@pytest.fixture(scope="module")
def wsess(rng=np.random.default_rng(13)):
    s = Session()
    s.execute("create table t (g varchar(2), v bigint, p decimal(8,2))")
    g = rng.choice(list("abcd"), 200)
    v = rng.integers(0, 40, 200)   # plenty of ties
    p = np.round(rng.uniform(0, 100, 200), 2)
    rows = ", ".join(f"('{g[i]}', {v[i]}, {p[i]})" for i in range(200))
    s.execute("insert into t values " + rows)
    df = pd.DataFrame({"g": g, "v": v, "p": p})
    return s, df


def _sorted_rows(rows):
    return sorted(rows)


def test_ranking_functions(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        row_number() over (partition by g order by v) rn,
        rank() over (partition by g order by v) rk,
        dense_rank() over (partition by g order by v) dr
        from t order by g, v, rn""").rows()
    d = df.sort_values(["g", "v"]).copy()
    d["rn"] = d.groupby("g").cumcount() + 1
    d["rk"] = d.groupby("g")["v"].rank(method="min").astype(int)
    d["dr"] = d.groupby("g")["v"].rank(method="dense").astype(int)
    exp = list(d[["g", "v", "rn", "rk", "dr"]].itertuples(index=False,
                                                          name=None))
    assert got == exp


def test_cumulative_sum_range_peers(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        sum(v) over (partition by g order by v) cs
        from t order by g, v""").rows()
    d = df.sort_values(["g", "v"]).copy()
    # RANGE frame: peers share the cumulative value of the last peer
    d["cs"] = d.groupby("g")["v"].cumsum()
    d["cs"] = d.groupby(["g", "v"])["cs"].transform("max")
    exp = list(d[["g", "v", "cs"]].itertuples(index=False, name=None))
    assert sorted(got) == sorted(exp)


def test_partition_totals_and_counts(wsess):
    s, df = wsess
    got = s.execute("""select g, sum(v) over (partition by g) t,
        count(*) over (partition by g) c from t order by g limit 4""").rows()
    sums = df.groupby("g")["v"].sum()
    counts = df.groupby("g")["v"].count()
    for g_, t_, c_ in got:
        assert t_ == sums[g_] and c_ == counts[g_]


def test_running_min_max_and_avg(wsess):
    s, df = wsess
    got = s.execute("""select g, v,
        min(v) over (partition by g order by v) mn,
        max(v) over (partition by g order by v) mx,
        avg(v) over (partition by g order by v) av
        from t order by g, v""").rows()
    d = df.sort_values(["g", "v"]).copy()
    d["mn"] = d.groupby("g")["v"].cummin()
    d["mx"] = d.groupby("g")["v"].cummax()
    d["cs"] = d.groupby("g")["v"].cumsum()
    d["cn"] = d.groupby("g").cumcount() + 1
    d["av"] = d["cs"] / d["cn"]
    for c in ("mn", "mx", "av"):
        d[c] = d.groupby(["g", "v"])[c].transform(
            "max" if c != "mn" else "min")
    # avg peers share last-peer value
    d["av"] = d.groupby(["g", "v"])["cs"].transform("max") / \
        d.groupby(["g", "v"])["cn"].transform("max")
    exp = {(r[0], r[1]): (r[2], r[3], round(r[4], 9))
           for r in d[["g", "v", "mn", "mx", "av"]].itertuples(
               index=False, name=None)}
    for g_, v_, mn, mx, av in got:
        emn, emx, eav = exp[(g_, v_)]
        assert mn == emn and mx == emx and abs(av - eav) < 1e-9


def test_window_without_partition(wsess):
    s, df = wsess
    got = s.execute("select v, row_number() over (order by v) rn "
                    "from t order by v, rn limit 3").rows()
    assert [r[1] for r in got] == [1, 2, 3]


def test_window_error_paths(wsess):
    s, _ = wsess
    with pytest.raises(Exception, match="not a window function"):
        s.execute("select upper(g) over (partition by g) from t")
    with pytest.raises(Exception, match="top-level"):
        s.execute("select 1 + row_number() over (order by v) from t")


def test_window_all_null_frame_yields_null():
    s = Session()
    s.execute("create table n (g varchar(2), v bigint)")
    s.execute("insert into n values ('a', null), ('a', null), ('b', 1)")
    rows = s.execute("""select g, sum(v) over (partition by g) sv,
        min(v) over (partition by g) mv from n order by g""").rows()
    assert rows[0] == ("a", None, None)
    assert rows[2] == ("b", 1, 1)


def test_window_over_group_by():
    s = Session()
    s.execute("create table t (g varchar(2), v bigint)")
    s.execute("insert into t values ('a',1),('a',2),('b',10),('b',20),('c',3)")
    rows = s.execute("""select g, sum(v) s,
        rank() over (order by sum(v) desc) rk
        from t group by g order by rk""").rows()
    assert rows == [("b", 30, 1), ("c", 3, 2), ("a", 3, 2)] or \
           rows == [("b", 30, 1), ("a", 3, 2), ("c", 3, 2)]


def test_window_invalid_forms():
    s = Session()
    s.execute("create table t (g varchar(2), v bigint)")
    s.execute("insert into t values ('a', 1)")
    with pytest.raises(Exception, match=r"sum\(\*\)"):
        s.execute("select sum(*) over (partition by g) from t")
    with pytest.raises(Exception, match="DISTINCT"):
        s.execute("select count(distinct v) over (partition by g) from t")
    with pytest.raises(Exception, match="strings"):
        s.execute("select min(g) over (partition by v) from t")

"""TPU compute worker over gRPC (reference analogue: udf pyserver tests +
cgo/cuvs worker lifecycle)."""

import numpy as np
import pytest

from matrixone_tpu.container import dtypes as dt
from matrixone_tpu.sql.serde import dtype_to_json, expr_to_json
from matrixone_tpu.sql.expr import BoundCol, BoundFunc, BoundLiteral
from matrixone_tpu.worker import TpuWorkerServer, WorkerClient


@pytest.fixture(scope="module")
def worker():
    srv = TpuWorkerServer(port=0).start()
    client = WorkerClient(f"127.0.0.1:{srv.port}")
    yield client
    client.close()
    srv.stop()


def test_health(worker):
    h = worker.health()
    assert h["backend"] in ("cpu", "tpu")
    assert h["stages_run"] == 0 or h["stages_run"] >= 0


def test_filter_project_stage(worker):
    n = 1000
    arrays = {"a": np.arange(n, dtype=np.int64),
              "b": np.linspace(0, 1, n)}
    validity = {c: np.ones(n, np.bool_) for c in arrays}
    schema = {"a": dtype_to_json(dt.INT64), "b": dtype_to_json(dt.FLOAT64)}
    col_a = BoundCol("a", dt.INT64)
    col_b = BoundCol("b", dt.FLOAT64)
    filt = BoundFunc("lt", [col_a, BoundLiteral(100, dt.INT64)], dt.BOOL)
    proj = {"a2": expr_to_json(BoundFunc("mul", [col_a,
                                                 BoundLiteral(2, dt.INT64)],
                                         dt.INT64)),
            "b": expr_to_json(col_b)}
    h, out, val = worker.filter_project(arrays, validity, schema,
                                        [expr_to_json(filt)], proj)
    assert len(out["a2"]) == 100
    np.testing.assert_array_equal(out["a2"], np.arange(100) * 2)


def test_index_lifecycle(worker):
    rng = np.random.default_rng(0)
    data = rng.standard_normal((3000, 24)).astype(np.float32)
    r = worker.load_index("ix1", data, nlist=12)
    assert r["ok"] and r["n"] == 3000
    q = data[:5] + 0.001
    dists, ids = worker.search_index("ix1", q, k=3, nprobe=12)
    assert ids.shape == (5, 3)
    # self-hit first
    np.testing.assert_array_equal(ids[:, 0], np.arange(5))
    assert worker.health()["indexes"] == ["ix1"]


def test_worker_error_surface(worker):
    with pytest.raises(RuntimeError, match="worker:"):
        worker.run({"op": "nope"})
    with pytest.raises(RuntimeError, match="worker:"):
        worker.search_index("missing_index", np.zeros((1, 4), np.float32))


def test_group_aggregate_stage(worker):
    from matrixone_tpu.sql.serde import agg_to_json
    from matrixone_tpu.sql.expr import AggCall
    from matrixone_tpu.storage import arrowio
    n = 500
    keys = np.arange(n) % 7
    vals = np.arange(n, dtype=np.int64)
    arrays = {"k": keys.astype(np.int64), "v": vals}
    validity = {c: np.ones(n, np.bool_) for c in arrays}
    kcol = BoundCol("k", dt.INT64)
    vcol = BoundCol("v", dt.INT64)
    h, b = worker.run(
        {"op": "group_aggregate",
         "schema": {"k": dtype_to_json(dt.INT64),
                    "v": dtype_to_json(dt.INT64)},
         "group_keys": [expr_to_json(kcol)],
         "aggs": [agg_to_json(AggCall("sum", vcol, False, dt.INT64,
                                      out_name="_agg0"))],
         "max_groups": 64},
        arrowio.arrays_to_ipc(arrays, validity))
    assert h["n_groups"] == 7
    out, _ = arrowio.ipc_to_arrays(b)
    got = dict(zip(out["_g0"][:7].tolist(), out["_a0_sum"][:7].tolist()))
    for g in range(7):
        assert got[g] == int(vals[keys == g].sum())


def test_dynamic_batching_coalesces_concurrent_searches(monkeypatch):
    """VERDICT r1 #7: concurrency-N search must coalesce into fewer
    device dispatches (cuvs dynamic_batching analogue).

    Deflaked (the PR-4 tier-1 run's one red): the old form fired 40
    unsynchronized threads at the shared 2ms-linger worker and demanded
    an ABSOLUTE dispatch bound (disp < reqs/2) — under background load
    the threads trickle into the queue slower than the production
    linger, the in-flight count the linger condition watches stays ~1,
    and the batcher correctly doesn't wait, failing the test for
    scheduler reasons.  The property under test is "concurrent requests
    coalesce through the linger", not "2ms outruns a loaded scheduler",
    so the test owns a worker with a TEST-SIZED linger window (50ms,
    hard-capped at 5x by the batcher): a warm-up search removes
    first-dispatch compile skew, a barrier releases the burst together,
    and the gate is a coalescing RATIO — any real loss of batching
    (e.g. the linger reverting to grab-instantly) still fails it by a
    mile, while 250ms absorbs any plausible scheduling delay."""
    import threading
    monkeypatch.setenv("MO_BATCH_LINGER_MS", "50")
    srv = TpuWorkerServer(port=0).start()
    worker = WorkerClient(f"127.0.0.1:{srv.port}")
    try:
        rng = np.random.default_rng(5)
        data = rng.normal(size=(2000, 8)).astype(np.float32)
        worker.load_index("batched", data, nlist=8)
        # warm the compiled search shape: the first dispatch otherwise
        # takes long enough that every straggler lands in dispatch #2
        # regardless of the linger (masking regressions) or, on a
        # loaded box, none do
        worker.search_index("batched", data[:1], k=1, nprobe=8)
        h0 = worker.health()
        results = [None] * 40
        barrier = threading.Barrier(40)

        def one(i):
            q = data[i * 3:i * 3 + 1]
            barrier.wait(timeout=60)      # burst-release together
            d, ids = worker.search_index("batched", q, k=1, nprobe=8)
            results[i] = int(ids[0][0])

        ts = [threading.Thread(target=one, args=(i,)) for i in range(40)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert all(results[i] == i * 3 for i in range(40)), results[:5]
        h1 = worker.health()
        reqs = h1["batch_requests"] - h0["batch_requests"]
        disp = h1["batch_dispatches"] - h0["batch_dispatches"]
        assert reqs == 40
        # >= 25% of requests must ride another request's dispatch:
        # loose enough for a loaded CI box, far above zero-coalescing
        coalesced = reqs - disp
        assert coalesced >= reqs * 0.25, (reqs, disp)
    finally:
        worker.close()
        srv.stop()


def test_sharded_and_replicated_modes(worker):
    rng = np.random.default_rng(6)
    data = rng.normal(size=(1200, 8)).astype(np.float32)
    q = data[17:18]
    for mode in ("sharded", "replicated"):
        r = worker.load_index(f"ix_{mode}", data, nlist=8, mode=mode)
        assert r["mode"] == mode
        d, ids = worker.search_index(f"ix_{mode}", q, k=3, nprobe=8)
        assert int(ids[0][0]) == 17, (mode, ids[0])


def test_sharded_mode_recall_parity(worker):
    """VERDICT r3 weak #9: sharded mode splits nlist arithmetically and
    recall at small shards was never measured. Clustered data, recall@10
    vs exact brute force: sharded must stay within 0.05 of single-index
    recall at the same nprobe budget."""
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(64, 16)).astype(np.float32) * 3.0
    labels = rng.integers(0, 64, 6000)
    data = (centers[labels]
            + rng.normal(size=(6000, 16)).astype(np.float32) * 0.4)
    queries = (centers[rng.integers(0, 64, 100)]
               + rng.normal(size=(100, 16)).astype(np.float32) * 0.4)
    # exact truth
    d2 = ((queries[:, None, :] - data[None, :, :]) ** 2).sum(-1)
    truth = np.argsort(d2, axis=1)[:, :10]

    def recall(name):
        _d, ids = worker.search_index(name, queries, k=10, nprobe=8)
        hit = sum(len(set(ids[i].tolist()) & set(truth[i].tolist()))
                  for i in range(len(queries)))
        return hit / truth.size

    worker.load_index("rp_single", data, nlist=32, mode="single")
    worker.load_index("rp_shard", data, nlist=32, mode="sharded")
    r_single = recall("rp_single")
    r_shard = recall("rp_shard")
    assert r_single > 0.8, r_single
    # sharded overfetches per shard and exact-reranks the merged union,
    # so it must MATCH OR BEAT the single index at the same nprobe
    assert r_shard >= r_single - 0.01, (r_shard, r_single)

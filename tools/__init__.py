# repo tooling package (`python -m tools.molint`, `python -m tools.precheck`)

"""Bench regression guard: fail when the latest round's headline metrics
regress >20% against the best earlier round.

The r05 postmortem was a scoreboard that silently stopped trending; the
serving PR adds caches that could just as silently eat the scan-path
wins of PRs 1/3.  This tool reads every BENCH_*.json in the repo (the
driver's per-round records: {"n": round, "tail": "...last stdout..."}),
extracts the one-line JSON metric contract (top-level + extra_metrics),
and compares the LATEST round against the best PRIOR value of the same
metric family on the same backend.  Shape suffixes are normalized away
(ivfflat_search_qps_200000x256_top20_nprobe8 -> ivfflat_search_qps) so
rounds at different scales still guard the family; only higher-is-better
units (qps, rows/s) are guarded.

Usage: python tools/bench_guard.py [--dir REPO] [--tolerance 0.2]
Exit 0 = no regression, 1 = regression (or latest round unreadable).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_GUARDED_UNITS = {"qps", "rows/s"}


def family(metric: str) -> str:
    """Strip shape/config suffixes: everything from the first numeric
    segment on (ivfflat_search_qps_200000x256_top20_nprobe8 and
    tpch_q1_rows_per_sec_6001215 both reduce to their family)."""
    parts = metric.split("_")
    out = []
    for p in parts:
        if re.fullmatch(r"\d+(x\d+)?(dev)?|top\d+|nprobe\d+(x\d+dev)?", p):
            break
        out.append(p)
    return "_".join(out) or metric


def _entries_of(path: str):
    """Every metric entry (top-level + extra_metrics) of one round
    record, or None if unreadable."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    lines = [ln for ln in str(rec.get("tail", "")).splitlines()
             if ln.startswith("{")]
    if not lines:
        return None
    try:
        top = json.loads(lines[-1])
    except ValueError:
        return None
    return int(rec.get("n", 0)), [top] + list(top.get("extra_metrics")
                                              or [])


def dispatch_counts_of(path: str) -> dict:
    """{(family, backend): fused_dispatches} for one round record —
    the per-family device-dispatch counts the budget check guards
    (LOWER is better: a fusion regression shows up as more dispatches
    long before wall-clock moves on a noisy box)."""
    got = _entries_of(path)
    out: dict = {}
    if got is None:
        return out
    for m in got[1]:
        cnt = m.get("fused_dispatches")
        if not isinstance(cnt, (int, float)) or cnt <= 0:
            continue
        key = (family(str(m.get("metric", ""))),
               str(m.get("backend", "")))
        out[key] = max(out.get(key, 0.0), float(cnt))
    return out


def metrics_of(path: str):
    """-> (round_n, {(family, backend): value}) or None if unreadable."""
    got = _entries_of(path)
    if got is None:
        return None
    rec_n, entries = got
    out = {}
    for m in entries:
        unit = m.get("unit")
        val = m.get("value")
        if unit not in _GUARDED_UNITS or not isinstance(val, (int, float)) \
                or val <= 0:
            continue
        key = (family(str(m.get("metric", ""))),
               str(m.get("backend", "")))
        out[key] = max(out.get(key, 0.0), float(val))
    return rec_n, out


def check(bench_dir: str, tolerance: float = 0.2):
    """-> (ok, report_lines)."""
    rounds = []
    unreadable = []
    # natural order so BENCH_r100 sorts after BENCH_r99 (lexicographic
    # order would break the newest-round detection at two-digit rounds);
    # BENCH_FLOORS.json is the floors sidecar, not a round record
    paths = sorted(
        (p for p in glob.glob(os.path.join(bench_dir, "BENCH_*.json"))
         if os.path.basename(p) != "BENCH_FLOORS.json"),
        key=lambda p: [int(t) if t.isdigit() else t for t in
                       re.split(r"(\d+)", os.path.basename(p))])
    for path in paths:
        got = metrics_of(path)
        if got is None:
            unreadable.append(os.path.basename(path))
        else:
            rounds.append((got[0], os.path.basename(path), got[1]))
    rounds.sort()
    report = []
    # the newest record being unreadable IS the failure this guard
    # exists for: a bench crash would otherwise drop the round and the
    # comparison would silently fall back to the previous one
    if paths and os.path.basename(paths[-1]) in unreadable:
        report.append(f"FAIL latest bench record "
                      f"{os.path.basename(paths[-1])} is unreadable — "
                      f"the newest round cannot be verified")
        return False, report
    for name in unreadable:
        report.append(f"WARN unreadable bench record {name} (skipped)")
    # explicit absolute floors override history — the escape hatch for a
    # deliberate methodology change (e.g. r05 rerouted Q1 through the
    # object store: honest numbers dropped, history would mis-flag it)
    floors = {}
    budgets = {}
    floors_path = os.path.join(bench_dir, "BENCH_FLOORS.json")
    if os.path.exists(floors_path):
        try:
            with open(floors_path) as f:
                raw = json.load(f)
            # "_"-prefixed keys are sidecar sections, not floor
            # families: _comment, and _dispatch_budgets — the
            # per-family device-dispatch ceilings (LOWER is better;
            # a broken fusion shows up as dispatch count long before
            # wall-clock moves on a share-throttled box)
            floors = {(fam, be): float(v)
                      for fam, per_be in raw.items()
                      if isinstance(per_be, dict)
                      and not fam.startswith("_")
                      for be, v in per_be.items()}
            budgets = {(fam, be): float(v)
                       for fam, per_be in
                       (raw.get("_dispatch_budgets") or {}).items()
                       if isinstance(per_be, dict)
                       for be, v in per_be.items()}
        except (OSError, ValueError, TypeError) as e:
            report.append(f"WARN unreadable {floors_path}: {e}")
    if len(rounds) < 2 and not floors:
        report.append(f"bench_guard: only {len(rounds)} readable round(s)"
                      f" in {bench_dir}; nothing to compare")
        return True, report
    if not rounds:
        report.append(f"bench_guard: no readable BENCH_*.json in "
                      f"{bench_dir}")
        return False, report
    latest_n, latest_name, latest = rounds[-1]
    best: dict = {}
    for n, name, ms in rounds[:-1]:
        for key, v in ms.items():
            if v > best.get(key, (0.0, ""))[0]:
                best[key] = (v, name)
    ok = True
    for key in sorted(set(best) | set(floors)):
        fam, backend = key
        cur = latest.get(key)
        if key in floors:
            floor_v, src = floors[key], "BENCH_FLOORS.json"
            floor = floor_v                  # absolute, pre-tolerated
        elif key in best:
            floor_v, src = best[key]
            floor = floor_v * (1.0 - tolerance)
        else:
            continue
        if cur is None:
            report.append(f"WARN {fam} [{backend}]: absent from "
                          f"{latest_name} (floor {floor_v:g} per {src})"
                          f" — config drift or a dropped trend line")
            continue
        if cur < floor:
            ok = False
            report.append(
                f"FAIL {fam} [{backend}]: {cur:g} in {latest_name} is "
                f"below floor {floor:g} (from {floor_v:g} per {src})")
        else:
            report.append(f"ok   {fam} [{backend}]: {cur:g} vs floor "
                          f"{floor:g} ({src})")
    # dispatch-count budgets (inverted guard: latest must stay AT OR
    # UNDER the ceiling) — only the latest round is judged; a family
    # absent from it is a WARN like the floor case above
    if budgets:
        counts = dispatch_counts_of(
            os.path.join(bench_dir, latest_name))
        for key in sorted(budgets):
            fam, backend = key
            cap = budgets[key]
            cur = counts.get(key)
            if cur is None:
                report.append(
                    f"WARN dispatch budget {fam} [{backend}]: no "
                    f"fused_dispatches in {latest_name} (budget "
                    f"{cap:g})")
                continue
            if cur > cap:
                ok = False
                report.append(
                    f"FAIL dispatch budget {fam} [{backend}]: "
                    f"{cur:g} dispatches in {latest_name} exceeds "
                    f"budget {cap:g} (fusion regression)")
            else:
                report.append(
                    f"ok   dispatch budget {fam} [{backend}]: "
                    f"{cur:g} <= {cap:g}")
    return ok, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--tolerance", type=float, default=0.2)
    args = ap.parse_args(argv)
    ok, report = check(args.dir, args.tolerance)
    for line in report:
        print(line)
    print("bench_guard:", "PASS" if ok else "REGRESSION")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Probe the TPU tunnel; run the full bench the moment it answers.
# Writes the JSON line to bench_r2_result.json on success.
cd /root/repo
for i in $(seq 1 100); do
  if timeout 90 python -c "import jax, jax.numpy as jnp; jnp.ones(8).sum().block_until_ready()" >/dev/null 2>&1; then
    echo "$(date -u +%T) probe ok, running bench (attempt $i)" >> bench_watch.log
    if timeout 2400 python bench.py > bench_r2_result.json 2> bench_r2_stderr.log; then
      echo "$(date -u +%T) bench done: $(cat bench_r2_result.json)" >> bench_watch.log
      exit 0
    else
      echo "$(date -u +%T) bench failed rc=$? (see bench_r2_stderr.log)" >> bench_watch.log
    fi
  else
    echo "$(date -u +%T) probe failed (attempt $i)" >> bench_watch.log
  fi
  sleep 300
done
exit 1

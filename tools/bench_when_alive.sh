#!/bin/bash
# Probe the TPU tunnel; run the full bench the moment it answers.
# Writes the JSON line to bench_r5_result.json on success.  A CPU-backend
# fallback result is recorded but does NOT stop the loop — the script
# exists to capture the on-chip number.
cd /root/repo
for i in $(seq 1 400); do
  if timeout 90 python -c "import jax, jax.numpy as jnp; jnp.ones(8).sum().block_until_ready()" >/dev/null 2>&1; then
    echo "$(date -u +%T) probe ok, running bench (attempt $i)" >> bench_watch.log
    if timeout 4800 python bench.py > bench_r5_result.json 2> bench_r5_stderr.log; then
      if grep -q '"backend": "cpu"' bench_r5_result.json; then
        # tunnel wedged between probe and preflight: the CPU fallback
        # answered — keep waiting for the chip
        echo "$(date -u +%T) got cpu fallback only, keep probing: $(cat bench_r5_result.json)" >> bench_watch.log
      else
        echo "$(date -u +%T) bench done: $(cat bench_r5_result.json)" >> bench_watch.log
        # also profile pallas vs xla distance kernel while the chip answers
        timeout 1200 python tools/profile_pallas.py > pallas_profile.json 2>> bench_r5_stderr.log \
          && echo "$(date -u +%T) pallas profile: $(cat pallas_profile.json)" >> bench_watch.log
        exit 0
      fi
    else
      rc=$?
      echo "$(date -u +%T) bench failed rc=$rc (see bench_r5_stderr.log)" >> bench_watch.log
    fi
  else
    echo "$(date -u +%T) probe failed (attempt $i)" >> bench_watch.log
  fi
  sleep 180
done
exit 1

#!/usr/bin/env python
"""(Re)generate BVT goldens: python tools/bvt_record.py [case.sql ...]

With no arguments, records every case under tests/bvt/cases. Review the
diff before committing — the goldens pin engine behavior (reference:
mo-tester regenerating .result files).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from matrixone_tpu.frontend import Session  # noqa: E402
from matrixone_tpu.utils import bvt  # noqa: E402

ROOT = os.path.join(os.path.dirname(__file__), "..", "tests", "bvt",
                    "cases")


def main() -> None:
    cases = sys.argv[1:] or bvt.iter_cases(ROOT)
    for path in cases:
        bvt.record(path, Session)
        print(f"recorded {os.path.relpath(path)}")


if __name__ == "__main__":
    main()

"""kernel-smoke: interpret-mode Pallas vs XLA bit-identity drill.

The hand kernels behind ops/kernels.py promise an exact contract:
`sorted_lookup` (the hash-join probe's searchsorted) is bit-identical
to `jnp.searchsorted(side='left')` on EVERY backend by construction —
an integer count has no rounding and no order sensitivity — and the
grouped-scatter f32 kernel is bit-identical whenever the elements and
partial sums are exactly representable (the drill uses small integers
so any deviation is a real kernel bug, not float noise).

This module proves both in interpret mode (<30s on the cpu test mesh),
plus a teeth-check: a deliberately wrong reference (searchsorted
side='right' over data WITH duplicates) must be flagged as a mismatch,
so a comparator bug cannot silently green the drill.

Run via `python -m tools.precheck --kernel-smoke`.
"""

from __future__ import annotations

import time


def run_smoke(seed: int = 7) -> dict:
    t0 = time.perf_counter()
    import jax.numpy as jnp
    import numpy as np

    from matrixone_tpu.ops import pallas_kernels as PK

    rng = np.random.default_rng(seed)
    checks = 0
    errors: list = []

    # ---- sorted_lookup: uint64 hashes with duplicate runs + the NULL
    # sentinel, queries mixing present / absent / extremes
    n, m = 3000, 2100                      # deliberately NOT tile-aligned
    base = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    base[: n // 4] = base[0]               # a fat duplicate run
    base[-8:] = np.uint64(0xFFFFFFFFFFFFFFFF)   # the NULL-hash region
    srt = np.sort(base)
    queries = np.concatenate([
        rng.choice(srt, size=m - 4),       # present (lands inside runs)
        np.array([0, 1, (1 << 64) - 1, srt[n // 2] + 1], dtype=np.uint64),
    ])
    s_j = jnp.asarray(srt)
    q_j = jnp.asarray(queries)
    got = np.asarray(PK.sorted_search_pallas(s_j, q_j, interpret=True))
    want = np.asarray(jnp.searchsorted(s_j, q_j)).astype(np.int64)
    checks += 1
    if not np.array_equal(got.astype(np.int64), want):
        bad = int(np.sum(got.astype(np.int64) != want))
        errors.append(f"sorted_search_pallas != searchsorted on "
                      f"{bad}/{m} queries")

    # teeth: side='right' differs on duplicate runs — the drill must
    # see that difference or its comparison proves nothing
    wrong = np.asarray(jnp.searchsorted(s_j, q_j, side="right"))
    plant_caught = not np.array_equal(got.astype(np.int64),
                                      wrong.astype(np.int64))

    # ---- grouped scatter: f32 segment sum over small integers (exact
    # in f32 at any summation order) vs the XLA scatter
    nrows, groups = 4096, 37
    vals = rng.integers(0, 16, size=nrows).astype(np.float32)
    gids = rng.integers(0, groups, size=nrows).astype(np.int32)
    mask = rng.random(nrows) < 0.9
    got_g = np.asarray(PK.segment_sum_pallas(
        jnp.asarray(vals), jnp.asarray(gids), jnp.asarray(mask),
        num_segments=groups, tile_n=512, interpret=True))
    import jax
    want_g = np.asarray(jax.ops.segment_sum(
        jnp.where(jnp.asarray(mask), jnp.asarray(vals), 0.0),
        jnp.asarray(gids), num_segments=groups)).astype(np.float32)
    checks += 1
    if not np.array_equal(got_g, want_g):
        bad = int(np.sum(got_g != want_g))
        errors.append(f"segment_sum_pallas != segment_sum on "
                      f"{bad}/{groups} groups")

    # ---- dispatch seam: the kill switch must actually route
    import os

    from matrixone_tpu.ops import kernels as HK
    was = os.environ.get("MO_HAND_KERNELS")
    try:
        os.environ["MO_HAND_KERNELS"] = "0"
        off = HK.enabled()
        os.environ["MO_HAND_KERNELS"] = "1"
        on = HK.enabled()
    finally:
        if was is None:
            os.environ.pop("MO_HAND_KERNELS", None)
        else:
            os.environ["MO_HAND_KERNELS"] = was
    checks += 1
    if off or not on:
        errors.append(f"MO_HAND_KERNELS routing broken: "
                      f"0->{off}, 1->{on}")
    # and the seam's XLA fallback answers the same lookup
    fb = np.asarray(jnp.searchsorted(s_j, q_j)).astype(np.int64)
    checks += 1
    if not np.array_equal(fb, got.astype(np.int64)):
        errors.append("seam XLA fallback disagrees with Pallas path")

    return {
        "checks": checks,
        "errors": errors,
        "plant_caught": plant_caught,
        "seconds": round(time.perf_counter() - t0, 2),
    }

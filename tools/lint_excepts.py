#!/usr/bin/env python
"""Fail on new broad exception swallowing in the cluster/frontend lanes.

A bare `except Exception`/`except BaseException`/`except:` in the RPC or
wire-protocol layers is how partial failures turn into silent data loss —
every broad catch there must either narrow its type or carry a
`# noqa: BLE001` comment with a justification (the convention the
existing annotated sites follow).

Usage: python tools/lint_excepts.py [repo_root]
Exit 0 = clean, 1 = findings (printed one per line as path:lineno).
"""

from __future__ import annotations

import os
import re
import sys

#: lanes where broad catches must be justified — the RPC/wire layers,
#: plus UDF execution and the worker service (user code runs there: a
#: silent broad except is exactly where a body error becomes wrong rows)
LINT_DIRS = ("matrixone_tpu/cluster", "matrixone_tpu/frontend",
             "matrixone_tpu/udf", "matrixone_tpu/worker")

#: bare `except:` or any except clause naming Exception/BaseException —
#: including tuple forms like `except (Exception, ValueError):`
_BROAD = re.compile(
    r"^\s*except\s*(:|[^:]*\b(Exception|BaseException)\b)")
_NOQA = re.compile(r"#\s*noqa")


def scan_file(path: str):
    findings = []
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    for i, line in enumerate(lines, 1):
        if not _BROAD.match(line):
            continue
        # the noqa may sit on the except line itself or (for short
        # lines) be the sole content of the line directly above
        prev = lines[i - 2] if i >= 2 else ""
        if _NOQA.search(line) or _NOQA.search(prev):
            continue
        findings.append((path, i, line.strip()))
    return findings


def main(root: str = ".") -> int:
    findings = []
    for d in LINT_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    findings.extend(scan_file(os.path.join(dirpath, fn)))
    for path, lineno, text in findings:
        print(f"{path}:{lineno}: unjustified broad except "
              f"(add a narrower type or '# noqa: BLE001 — why'): {text}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "."))

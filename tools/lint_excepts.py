#!/usr/bin/env python
"""Thin shim over `tools.molint`'s broad-except checker (the original
standalone linter was folded into the molint suite, which now covers
the WHOLE package rather than four hand-picked lanes).

Kept so existing invocations and CI wiring don't break:

Usage: python tools/lint_excepts.py [repo_root]
Exit 0 = clean, 1 = findings (printed one per line as path:lineno).

New code should run `python -m tools.molint` (all rules) or
`python -m tools.molint --rule broad-except`.
"""

from __future__ import annotations

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))   # script-mode: find tools/


def main(root: str = ".") -> int:
    from tools import molint
    root = os.path.abspath(root)
    findings, _stats = molint.run_checks(
        root, src_paths=[os.path.join(root, "matrixone_tpu")],
        rules=["broad-except"], record=False)
    # the runner also surfaces parse/suppression meta-findings; this
    # legacy surface reports ONLY its own rule (run the full
    # `python -m tools.molint` for everything else)
    findings = [f for f in findings if f.rule == "broad-except"]
    for f in findings:
        # f.message already carries the full guidance text
        print(f"{f.path}:{f.lineno}: {f.message}")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "."))

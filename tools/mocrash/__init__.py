"""mocrash — deterministic crash-point recovery sweep.

The fifth analysis leg (molint static / mosan concurrency / moqa
differential / mokey key-completeness / mocrash durability): every
durability mechanism in this repo — the CRC-framed WAL, checkpoint
manifests, the quorum log, mview/CDC watermarks — is crash-TESTED, not
crash-hoped.  In the ALICE tradition:

  * a `RecordingFileService` (storage/fileservice.py) journals every
    write/append/fsync/replace as an ordered event log
    (utils/crash.CrashJournal);
  * seeded workloads (tools/mocrash/workload.py) run commits, DDL,
    snapshots, a maintained materialized view, CDC mirroring with a
    durable watermark, checkpoint, merge and quorum appends over
    recording file services, logging which operations were ACKED at
    which journal position; the `merge` scenario drives background
    MergeScheduler cycles under traffic so every scheduler decision
    point (candidate pick / off-lock rewrite / catalog swap / fence
    GC / checkpoint truncate) gets crashed;
  * the sweep "crashes" at every journal event under torn-tail and
    fsync-loss variants, materializes the surviving on-disk prefix,
    reopens the engine / replica set from it, and checks the recovery
    invariants (tools/mocrash/invariants.py): acked commits survive,
    in-flight commits are atomic, replay stops cleanly at torn frames,
    the mview and CDC mirror reconverge exactly-once from their
    watermarks, orphan tmp files are GC'd, quorum-acked entries are in
    every majority union;
  * five planted violations (tools/mocrash/plants.py) prove the net
    catches: rename-before-fsync, WAL-truncate-before-checkpoint-
    durable, watermark-advance-before-backing-commit, object-GC-before-
    fence-release-durable, merge-swap-before-rewrite-durable.

Gates: tests/test_mocrash.py runs a quick seeded sweep in tier-1 (zero
findings fails the build); `python -m tools.precheck --crash-smoke` is
the CI one-shot; `mo_ctl('crash','status'|'run:<seed>')` is the ops
surface.  Knobs (README "Crash consistency"): MO_CRASH_RECORD,
MO_CRASH_SEED, MO_CRASH_POINTS.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from matrixone_tpu.utils import crash

from tools.mocrash import invariants, plants, workload

#: torn fraction of the in-flight event x drop-unsynced-bytes mode.
#: quick covers the three distinct behaviours (pure ordering, torn
#: tail, maximum fsync loss); full adds the mixed cases.
VARIANTS_QUICK = [(1.0, False), (0.5, False), (0.0, True)]
VARIANTS_FULL = VARIANTS_QUICK + [(0.5, True), (1.0, True)]


def sweep_seed(default: int = 2026) -> int:
    """MO_CRASH_SEED: the tier-1 sweep's workload seed."""
    try:
        return int(os.environ.get("MO_CRASH_SEED", "") or default)
    except ValueError:
        return default


def sweep_points(default: int = 0) -> int:
    """MO_CRASH_POINTS: cap on crash points per scenario (0 = every
    journal event)."""
    try:
        return int(os.environ.get("MO_CRASH_POINTS", "") or default)
    except ValueError:
        return default


def _pick_points(n: int, cap: Optional[int]) -> List[int]:
    if not cap or cap <= 0 or cap >= n:
        return list(range(n))
    step = n / cap
    return sorted({int(i * step) for i in range(cap)})


def _sweep_world(world, checker, variants, pts, findings,
                 counts) -> None:
    """Crash/recover/check at each point in `pts` under every variant;
    recovery verdicts memoized on the materialized state + the visible
    ack prefix (many variants collapse to identical disk states).  The
    universe materializes ONCE per point-variant and is handed to the
    checker — the recovery reopens exactly the fingerprinted state."""
    memo = {}
    for k in pts:
        acked_sig = tuple(i for i, a in enumerate(world.acks)
                          if a.event_hi <= k)
        for torn, lossy in variants:
            var = invariants.variant_name(torn, lossy)
            crash.note_point(var)
            counts["points"] += 1
            u = world.journal.materialize(k, torn, lossy)
            key = (crash.universe_digest(u), acked_sig)
            if key in memo:
                counts["memo_hits"] += 1
                continue
            fnds = checker(world, k, torn, lossy, u=u)
            memo[key] = bool(fnds)
            counts["recoveries"] += 1
            crash.note_recovery(not fnds)
            for f in fnds:
                crash.note_finding(f.invariant)
            findings.extend(fnds)


def _plant_points(name: str, journal) -> List[int]:
    """Crash points covering a plant's violation window (a full-journal
    sweep would find them too — this keeps the drills fast)."""
    evs = journal.events()
    idxs: set = set()
    for i, e in enumerate(evs):
        if name == "truncate-early" and e.tag == "tn" \
                and e.op == "write_tmp" and e.path == "wal/wal.log.tmp":
            idxs.update(range(i, min(i + 40, len(evs))))
        elif name == "fsync-skip" and e.op == "replace" \
                and e.path.endswith("manifest.json.tmp"):
            idxs.update(range(i, min(i + 10, len(evs))))
        elif name == "watermark-early" and e.op == "write_tmp" \
                and e.path.endswith(".wm.tmp"):
            idxs.update(range(i, min(i + 30, len(evs))))
        elif name == "gc-early" and e.tag == "tn" \
                and e.op == "delete" and e.path.startswith("objects/"):
            # planted: old objects deleted BEFORE the fence-free
            # manifest replace — the violation window sits between
            idxs.update(range(i, min(i + 15, len(evs))))
        elif name == "swap-early" and e.tag == "tn" \
                and e.op == "write_tmp" and "/merge" in e.path:
            # planted: the unsynced merged object stays vulnerable from
            # its write through the checkpoint that references it (a 40-
            # event window keeps the drill fast; the violation fires
            # across the whole stretch)
            idxs.update(range(i, min(i + 40, len(evs))))
    return sorted(idxs)


def run_sweep(seed: Optional[int] = None, points: Optional[int] = None,
              variants: str = "quick", scenario: str = "all",
              plant: Optional[str] = None) -> dict:
    """Run workload(s), then crash/recover/check at every selected
    point.  Returns {findings, findings_formatted, points, recoveries,
    memo_hits, events, seconds, seed, scenario, plant}."""
    t0 = time.monotonic()
    seed = sweep_seed() if seed is None else seed
    if points is None:
        points = sweep_points()
    vlist = VARIANTS_FULL if variants == "full" else VARIANTS_QUICK
    findings: List[invariants.Finding] = []
    counts = {"points": 0, "recoveries": 0, "memo_hits": 0,
              "events": 0}

    def build_and_sweep():
        if scenario in ("engine", "all"):
            world = workload.run_engine_workload(seed)
            counts["events"] += len(world.journal)
            pts = (_plant_points(plant, world.journal)
                   if plant is not None
                   else _pick_points(len(world.journal), points))
            _sweep_world(world, invariants.check_engine, vlist, pts,
                         findings, counts)
        if scenario in ("merge", "all"):
            mw = workload.run_merge_workload(seed)
            counts["events"] += len(mw.journal)
            pts = (_plant_points(plant, mw.journal)
                   if plant is not None
                   else _pick_points(len(mw.journal), points))
            _sweep_world(mw, invariants.check_engine, vlist, pts,
                         findings, counts)
        if scenario in ("quorum", "all") and plant is None:
            qw = workload.run_quorum_workload(seed)
            counts["events"] += len(qw.journal)
            _sweep_world(qw, invariants.check_quorum, vlist,
                         _pick_points(len(qw.journal), points),
                         findings, counts)

    if plant is not None:
        with plants.plant(plant):
            build_and_sweep()
    else:
        build_and_sweep()

    rep = {"seed": seed, "scenario": scenario, "plant": plant,
           "variants": [invariants.variant_name(t, lo)
                        for t, lo in vlist],
           "events": counts["events"], "points": counts["points"],
           "recoveries": counts["recoveries"],
           "memo_hits": counts["memo_hits"],
           "findings": [f.__dict__ for f in findings],
           "findings_formatted": [f.format() for f in findings],
           "seconds": round(time.monotonic() - t0, 2)}
    crash.set_last_run({k: rep[k] for k in
                        ("seed", "scenario", "plant", "events",
                         "points", "recoveries", "seconds")}
                       | {"findings": len(findings)})
    return rep


def run_smoke(seed: Optional[int] = None) -> dict:
    """The precheck one-shot: one clean capped sweep (engine + merge +
    quorum) + two planted drills; <60s on the tier-1 box."""
    seed = sweep_seed() if seed is None else seed
    rep = run_sweep(seed=seed, points=60, scenario="all")
    planted = run_sweep(seed=seed, scenario="engine",
                        plant="truncate-early")
    rep["plant_caught"] = any(
        f["invariant"] == "acked-commit-lost"
        for f in planted["findings"])
    rep["plant_findings"] = len(planted["findings"])
    merge_planted = run_sweep(seed=seed, scenario="merge",
                              plant="gc-early")
    rep["merge_plant_caught"] = any(
        f["invariant"] == "gc-reachable-object-deleted"
        for f in merge_planted["findings"])
    rep["merge_plant_findings"] = len(merge_planted["findings"])
    return rep


def last_run_status() -> dict:
    """mo_ctl('crash','status') payload (the tools half)."""
    return crash.report() | {
        "variants_quick": [invariants.variant_name(t, lo)
                           for t, lo in VARIANTS_QUICK],
        "plants": plants.plant_names()}


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m tools.mocrash",
        description="deterministic crash-point recovery sweep (see "
                    "README 'Crash consistency')")
    ap.add_argument("--seed", type=int, default=None,
                    help="workload seed (default MO_CRASH_SEED or 2026)")
    ap.add_argument("--points", type=int, default=None,
                    help="cap on crash points per scenario (default "
                         "MO_CRASH_POINTS or all)")
    ap.add_argument("--variants", choices=("quick", "full"),
                    default="quick")
    ap.add_argument("--scenario",
                    choices=("engine", "merge", "quorum", "all"),
                    default="all")
    ap.add_argument("--plant", default=None,
                    choices=plants.plant_names(),
                    help="run with a planted violation; exit 0 iff the "
                         "sweep catches it")
    ap.add_argument("--smoke", action="store_true",
                    help="the precheck smoke (capped clean sweep + one "
                         "planted drill)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.smoke:
        rep = run_smoke(args.seed)
        print(json.dumps({k: rep[k] for k in
                          ("seed", "events", "points", "recoveries",
                           "seconds", "plant_caught",
                           "merge_plant_caught")}, sort_keys=True))
        for line in rep["findings_formatted"]:
            print(line)
        return 0 if not rep["findings"] and rep["plant_caught"] \
            and rep["merge_plant_caught"] else 1

    rep = run_sweep(seed=args.seed, points=args.points,
                    variants=args.variants, scenario=args.scenario,
                    plant=args.plant)
    if args.json:
        print(json.dumps(rep, indent=1, sort_keys=True, default=str))
    else:
        for line in rep["findings_formatted"]:
            print(line)
        print(json.dumps({k: rep[k] for k in
                          ("seed", "scenario", "events", "points",
                           "recoveries", "memo_hits", "seconds")},
                         sort_keys=True))
    if args.plant:
        print("planted violation CAUGHT" if rep["findings"]
              else "planted violation NOT caught", file=sys.stderr)
        return 0 if rep["findings"] else 1
    return 1 if rep["findings"] else 0


__all__ = ["run_sweep", "run_smoke", "last_run_status", "main",
           "VARIANTS_QUICK", "VARIANTS_FULL"]

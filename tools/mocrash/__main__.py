import sys

from tools.mocrash import main

if __name__ == "__main__":
    sys.exit(main())

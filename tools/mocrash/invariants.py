"""mocrash recovery invariants: reopen the system from one materialized
crash state and verify the durability contract.

Engine scenario (per crash point x torn/lossy variant):

  * recovery-opens        — Engine.open must succeed from ANY
                            crash-consistent state (torn WAL tails and
                            half-replaced manifests are normal crash
                            debris, never fatal);
  * acked-commit-lost     — every commit acknowledged before the crash
                            point is visible after reopen;
  * partial-commit-visible / phantom-rows — the one in-flight commit is
                            all-or-nothing; nothing else appears;
  * txn-atomicity         — a multi-table txn lands in both tables or
                            neither;
  * ddl-lost              — acked DDL (tables, snapshots, view defs)
                            survives;
  * orphan-gc             — Engine.open sweeps `*.tmp` crash leftovers;
  * recovery-summary      — the reopen reports its recovery summary;
  * mview-exactly-once    — after the first post-restart commit the
                            materialized view equals a recompute of its
                            defining query over the recovered base
                            table (no gap, no double-apply);
  * cdc-exactly-once      — resuming the mirror from its durable
                            watermark via cdc.delta_events converges
                            the mirror to the source exactly once
                            (re-seeding from 0 only when the delta
                            floor passed the watermark — a fence still
                            covering the resume must serve it);
  * asof-read             — an acked named snapshot reads bit-identical
                            to the view pinned at its creation, across
                            background merges (the merge fence serves
                            the pre-merge history);
  * gc-reachable-object-deleted — every object file referenced by a
                            live segment or a held merge fence exists:
                            fence GC goes manifest-durable-first, so a
                            crash leaks unreferenced files but never
                            deletes reachable ones.

Quorum scenario:

  * quorum-acked-lost     — every majority-acked entry (not yet
                            checkpoint-truncated) is present with an
                            intact payload in the union of EVERY
                            majority subset of replicas;
  * quorum-replica-load   — a replica reopens cleanly from any torn
                            state (tails drop, epochs never corrupt).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from matrixone_tpu.cdc import CdcTask, FileWatermark
from matrixone_tpu.logservice.replicated import ReplicaCore, merge_majority
from matrixone_tpu.storage.engine import ROWID, Engine
from matrixone_tpu.storage.fileservice import MemoryFS

from tools.mocrash import workload as W


@dataclasses.dataclass
class Finding:
    point: int
    event: str
    variant: str
    invariant: str
    detail: str

    def format(self) -> str:
        return (f"mocrash: point={self.point} event={self.event} "
                f"variant={self.variant} "
                f"invariant={self.invariant}: {self.detail}")


def variant_name(torn: float, lossy: bool) -> str:
    return f"torn{int(torn * 100)}" + ("+lossy" if lossy else "")


def _read_main(eng: Engine, table: str = "t_main",
               snapshot_ts: Optional[int] = None) -> Dict[int, tuple]:
    """id -> (batch, v, s) of the visible rows (or the AS OF view)."""
    t = eng.get_table(table)
    out: Dict[int, tuple] = {}
    for arrays, validity, dicts, n in t.iter_chunks(
            ["id", "batch", "v", "s"], 1 << 20, snapshot_ts=snapshot_ts):
        for i in range(n):
            s = (dicts["s"][int(arrays["s"][i])]
                 if validity["s"][i] else None)
            out[int(arrays["id"][i])] = (
                int(arrays["batch"][i]) if validity["batch"][i] else None,
                int(arrays["v"][i]) if validity["v"][i] else None, s)
    return out


def _read_pair(eng: Engine) -> set:
    t = eng.get_table("t_pair")
    out = set()
    for arrays, _v, _d, n in t.iter_chunks(["id"], 1 << 20):
        for i in range(n):
            out.add(int(arrays["id"][i]))
    return out


def _read_mview(eng: Engine) -> Dict[Optional[str], tuple]:
    t = eng.get_table("mv1")
    cols = [c for c, _ in t.meta.schema]          # s, sv, c
    out: Dict[Optional[str], tuple] = {}
    for arrays, validity, dicts, n in t.iter_chunks(cols, 1 << 20):
        for i in range(n):
            key = (dicts[cols[0]][int(arrays[cols[0]][i])]
                   if validity[cols[0]][i] else None)
            out[key] = (int(arrays[cols[1]][i]),
                        int(arrays[cols[2]][i]))
    return out


def _mview_oracle(main: Dict[int, tuple]
                  ) -> Dict[Optional[str], tuple]:
    groups: Dict[Optional[str], List[tuple]] = {}
    for _id, (_b, v, s) in main.items():
        groups.setdefault(s, []).append((v,))
    return {s: (sum(v for (v,) in rows if v is not None), len(rows))
            for s, rows in groups.items()}


def check_engine(world: "W.EngineWorld", k: int, torn: float,
                 lossy: bool, u: Optional[dict] = None
                 ) -> List[Finding]:
    evs = world.journal.events()
    label = evs[k].label() if k < len(evs) else "end"
    var = variant_name(torn, lossy)

    def F(inv: str, detail: str) -> Finding:
        return Finding(k, label, var, inv, detail)

    if u is None:
        u = world.journal.materialize(k, torn, lossy)
    tn_fs = u.get("tn") or MemoryFS()
    try:
        eng = Engine.open(tn_fs)
    except Exception as e:   # noqa: BLE001 — a recovery that cannot
        # open from a disciplined crash state IS the finding
        return [F("recovery-opens",
                  f"Engine.open raised {type(e).__name__}: {e}")]
    findings: List[Finding] = []
    if eng.recovery_summary is None:
        findings.append(F("recovery-summary",
                          "Engine.open emitted no recovery summary"))
    left = tn_fs.orphans()
    if left:
        findings.append(F("orphan-gc",
                          f"orphan tmp files survived open: {left}"))

    expected, pair_exp, ddl, inflight = world.fold(k)

    # ---- acked DDL survives
    for name in sorted(ddl):
        if inflight is not None and inflight.op == "snapdrop" \
                and inflight.table == name:
            continue       # the in-flight drop may have applied
        if name.startswith("snap"):
            if name not in eng.snapshots:
                findings.append(F("ddl-lost",
                                  f"acked snapshot {name} missing"))
        elif name not in eng.tables:
            findings.append(F("ddl-lost", f"acked {name!r} missing"))
    if "t_main" not in ddl or "t_main" not in eng.tables:
        return findings          # nothing further can be checked

    # ---- every object a live segment or a held merge fence references
    # must still exist: fence GC must go manifest-durable-first, so a
    # crash can only leak unreferenced files, never delete reachable ones
    missing = sorted({
        s.obj_path for t2 in eng.tables.values()
        for s in list(t2.segments) + [fs_ for f2 in
                                      getattr(t2, "fences", [])
                                      for fs_ in f2.segments]
        if s.obj_path is not None and not tn_fs.exists(s.obj_path)})
    if missing:
        findings.append(F(
            "gc-reachable-object-deleted",
            f"{len(missing)} reachable object file(s) gone: "
            f"{missing[:4]}"))
        return findings     # reads below would just raise on them

    # ---- acked commits visible, in-flight commit all-or-nothing
    try:
        actual = _read_main(eng)
        actual_pair = (_read_pair(eng) if "t_pair" in eng.tables
                       else set())
    except Exception as e:   # noqa: BLE001 — an unreadable recovered
        # table (torn object bytes behind a durable manifest) IS the
        # durability finding, not a sweep error
        findings.append(F("acked-commit-lost",
                          f"recovered table unreadable: "
                          f"{type(e).__name__}: {e}"))
        return findings

    # ---- AS OF reads through a surviving snapshot stay bit-exact
    # across background merges (the fence serves the pre-merge view)
    for a in world.acks:
        if a.op != "snapshot" or a.event_hi > k or not a.rows \
                or a.table not in eng.snapshots:
            continue
        try:
            got = _read_main(eng, snapshot_ts=eng.snapshots[a.table])
        except Exception as e:   # noqa: BLE001 — same rung as above
            findings.append(F("asof-read",
                              f"AS OF {a.table} raised "
                              f"{type(e).__name__}: {e}"))
            continue
        if got != a.rows:
            miss = sorted(set(a.rows) - set(got))[:6]
            extra = sorted(set(got) - set(a.rows))[:6]
            findings.append(F(
                "asof-read",
                f"AS OF {a.table} diverged from its pinned view "
                f"(missing ids {miss}, extra {extra})"))
    candidates: List[Tuple[Dict[int, tuple], set]] = [
        (expected, pair_exp)]
    if inflight is not None:
        if inflight.op in ("insert", "txn2"):
            alt = dict(expected)
            alt.update(inflight.rows)
            candidates.append((alt, pair_exp
                               | set(inflight.pair_ids)))
        elif inflight.op == "delete":
            alt = {i: r for i, r in expected.items()
                   if i not in inflight.ids}
            candidates.append((alt, pair_exp))
    if (actual, actual_pair) not in [tuple(c) for c in candidates]:
        findings.append(_classify(F, actual, actual_pair, expected,
                                  pair_exp, inflight))
        return findings     # downstream comparisons would double-report

    # ---- the delta economy reconverges exactly once
    if "mv1" in ddl:
        try:
            eng.commit_txn(None, {}, {})    # first post-restart commit
            #                                 drives the mview rebuild
            mv = _read_mview(eng)
            oracle = _mview_oracle(_read_main(eng))
            if mv != oracle:
                findings.append(F(
                    "mview-exactly-once",
                    f"view {mv} != recompute {oracle}"))
        except Exception as e:   # noqa: BLE001 — see recovery-opens
            findings.append(F("mview-exactly-once",
                              f"catch-up raised "
                              f"{type(e).__name__}: {e}"))
    findings.extend(_check_cdc(world, F, u, eng))
    return findings


def _check_cdc(world, F, u, eng) -> List[Finding]:
    mirror_fs = u.get("mirror") or MemoryFS()
    try:
        meng = W.mirror_engine(mirror_fs)
        wm = FileWatermark(mirror_fs, world.mirror_wm_path)
        task = CdcTask(eng, "t_main",
                       W.EngineSink(meng, "t_main"),
                       from_ts=wm.load())
        try:
            task.backfill(from_ts=task.watermark)
        except ValueError as e:
            # only a GC'd fence may refuse: below-or-at the delta floor
            # the re-seed is the documented degrade rung; a refusal
            # ABOVE the floor means the fence failed to serve a resume
            # it still covers — that's the finding, not a fallback
            floor = getattr(eng.get_table("t_main"), "delta_floor", 0)
            if task.watermark > floor:
                return [F("cdc-exactly-once",
                          f"fenced resume refused above the delta "
                          f"floor ({task.watermark} > {floor}): {e}")]
            W._clear_table(meng, "t_main")
            task.watermark = 0
            task.backfill(from_ts=0)
        wm.store(task.watermark)
        got = _read_main(meng)
        src = _read_main(eng)
        if got != src:
            missing = sorted(set(src) - set(got))[:6]
            extra = sorted(set(got) - set(src))[:6]
            return [F("cdc-exactly-once",
                      f"mirror diverged after watermark resume "
                      f"(missing ids {missing}, extra {extra})")]
    except Exception as e:   # noqa: BLE001 — see recovery-opens
        return [F("cdc-exactly-once",
                  f"mirror resume raised {type(e).__name__}: {e}")]
    return []


def _classify(F, actual, actual_pair, expected, pair_exp,
              inflight) -> Finding:
    lost = [i for i in expected if i not in actual
            or actual[i] != expected[i]]
    if lost:
        return F("acked-commit-lost",
                 f"{len(lost)} acked row(s) missing/changed, ids "
                 f"{sorted(lost)[:6]}")
    if inflight is not None and inflight.op == "txn2":
        got_main = all(i in actual for i in inflight.ids)
        got_pair = set(inflight.pair_ids) <= actual_pair
        if got_main != got_pair:
            return F("txn-atomicity",
                     f"multi-table txn half-applied (t_main={got_main}"
                     f", t_pair={got_pair})")
    if inflight is not None and inflight.op in ("insert", "txn2"):
        got = [i for i in inflight.ids if i in actual]
        if 0 < len(got) < len(inflight.ids):
            return F("partial-commit-visible",
                     f"in-flight insert partially visible: "
                     f"{len(got)}/{len(inflight.ids)} rows")
    extra = [i for i in actual if i not in expected
             and (inflight is None or i not in inflight.ids)]
    if extra:
        return F("phantom-rows",
                 f"rows never acked nor in flight: {sorted(extra)[:6]}")
    if actual_pair != pair_exp and (
            inflight is None
            or actual_pair != pair_exp | set(inflight.pair_ids)):
        return F("acked-commit-lost",
                 f"t_pair diverged: {sorted(actual_pair)} vs "
                 f"{sorted(pair_exp)}")
    return F("state-divergence",
             "recovered state matches no legal ack prefix")


# ------------------------------------------------------------- quorum

def check_quorum(world: "W.QuorumWorld", k: int, torn: float,
                 lossy: bool, u: Optional[dict] = None
                 ) -> List[Finding]:
    evs = world.journal.events()
    label = evs[k].label() if k < len(evs) else "end"
    var = variant_name(torn, lossy)
    if u is None:
        u = world.journal.materialize(k, torn, lossy)
    cores = []
    for i in range(world.n_replicas):
        try:
            cores.append(ReplicaCore(u.get(f"rep{i}") or MemoryFS()))
        except Exception as e:   # noqa: BLE001 — a replica that cannot
            # reload from its own crash state IS the finding
            return [Finding(k, label, var, "quorum-replica-load",
                            f"rep{i} reload raised "
                            f"{type(e).__name__}: {e}")]
    trunc_upto = 0
    for a in world.acks:
        # exemption starts the moment the truncate STARTED: a partially
        # propagated truncation may legitimately have dropped entries
        if a.op == "qtruncate" and a.event_lo <= k:
            trunc_upto = max(trunc_upto, a.upto)
    acked = [a for a in world.acks
             if a.op == "qappend" and a.event_hi <= k
             and a.seq > trunc_upto]
    findings: List[Finding] = []
    n = world.n_replicas
    for pair in [(i, j) for i in range(n) for j in range(i + 1, n)]:
        reads = [(cores[i].truncated_upto,
                  {s: p for s, (_e, p) in cores[i].entries.items()})
                 for i in pair]
        upto, merged = merge_majority(reads)
        for a in acked:
            if a.seq <= upto:
                continue
            if merged.get(a.seq) != a.payload:
                findings.append(Finding(
                    k, label, var, "quorum-acked-lost",
                    f"seq {a.seq} acked by a majority but absent/"
                    f"corrupt in the union of replicas {pair}"))
    return findings

"""Planted durability violations — the fixture-pair proof that the
mocrash sweep actually catches the bug classes it exists for (the
mosan/moqa/mokey plant discipline: re-introduce the historical bug,
assert the net catches it, restore).

  * fsync-skip        — the writer renames its tmp file into place
                        WITHOUT fsyncing it first: after a crash the
                        rename can expose a torn/empty file under the
                        final name (the classic zero-length-manifest
                        bug).  Planted in the RECORDED event stream
                        only, so the sweep sees the undisciplined
                        sequence while the live engine stays correct.
  * truncate-early    — WalWriter.truncate() runs BEFORE the checkpoint
                        manifest is durably renamed: a crash between
                        the two loses the whole tail (old manifest, no
                        WAL) — every acked commit since the previous
                        checkpoint vanishes.
  * watermark-early   — the CDC mirror persists its watermark BEFORE
                        the deliveries it covers are durable
                        downstream: a crash in between makes the resume
                        skip history — a silent gap in the mirror.
  * gc-early          — fence GC deletes the pre-merge object files
                        BEFORE the fence-free manifest is durable: a
                        crash in between leaves a manifest whose merge
                        fences reference vanished objects — AS OF and
                        fenced delta reads hit missing files.
  * swap-early        — the merge swap publishes the merged segment
                        while its object write skipped fsync (rewrite
                        not durable before the swap): a crash after the
                        referencing checkpoint can lose the only copy
                        of every merged row.

Each must be caught by the sweep with the point-of-crash and the
violated invariant named in the finding (tests/test_mocrash.py).
"""

from __future__ import annotations

import contextlib

from matrixone_tpu.storage.engine import Engine
from matrixone_tpu.storage.fileservice import RecordingFileService

from tools.mocrash import workload

_PLANTS = ("fsync-skip", "truncate-early", "watermark-early",
           "gc-early", "swap-early")


def plant_names():
    return list(_PLANTS)


@contextlib.contextmanager
def plant(name: str):
    if name == "fsync-skip":
        prev = RecordingFileService.SKIP_WRITE_FSYNC
        RecordingFileService.SKIP_WRITE_FSYNC = True
        try:
            yield
        finally:
            RecordingFileService.SKIP_WRITE_FSYNC = prev
    elif name == "truncate-early":
        orig = Engine._checkpoint_locked

        def early_truncate(self, demote=None):
            # the violation: the WAL tail is gone before the manifest
            # that supersedes it is durable (orig truncates again at
            # the correct point; truncating an empty log is a no-op)
            self.wal.truncate()
            return orig(self, demote=demote)

        Engine._checkpoint_locked = early_truncate
        try:
            yield
        finally:
            Engine._checkpoint_locked = orig
    elif name == "watermark-early":
        prev = workload.WM_EARLY
        workload.WM_EARLY = True
        try:
            yield
        finally:
            workload.WM_EARLY = prev
    elif name == "gc-early":
        prev = Engine.GC_DELETE_BEFORE_FENCE_RELEASE
        Engine.GC_DELETE_BEFORE_FENCE_RELEASE = True
        try:
            yield
        finally:
            Engine.GC_DELETE_BEFORE_FENCE_RELEASE = prev
    elif name == "swap-early":
        orig = Engine._merge_write_object

        def unsynced(self, name_, arrays, validity):
            # the violation: the merged object lands via rename with NO
            # fsync — the swap (and the checkpoint that references it)
            # proceed against a write the disk may not hold
            prev = RecordingFileService.SKIP_WRITE_FSYNC
            RecordingFileService.SKIP_WRITE_FSYNC = True
            try:
                return orig(self, name_, arrays, validity)
            finally:
                RecordingFileService.SKIP_WRITE_FSYNC = prev

        Engine._merge_write_object = unsynced
        try:
            yield
        finally:
            Engine._merge_write_object = orig
    else:
        raise ValueError(f"unknown plant {name!r}; use {_PLANTS}")

"""mocrash seeded workloads: run a realistic write history on
recording file services and log which operations were ACKNOWLEDGED at
which journal position — the ground truth the recovery invariants are
checked against (tools/mocrash/invariants.py).

Two scenarios:

  * engine — one TN engine (commits, DDL, snapshots, a materialized
    view maintained from deltas, checkpoint, merge, a multi-table
    atomic txn) plus a CDC mirror engine on its own file service with
    a durably persisted watermark; both journals share ONE CrashJournal
    so a crash point is a consistent cut across source and mirror;
  * quorum — three log-replica cores driven by a majority-ack writer
    (the ReplicatedLog append rule), with a mid-stream checkpoint
    truncation.

Determinism: the SHAPE of the workload (row counts, values, strings,
delete choices) is seeded; timestamps are wall-clock HLC and don't
matter to any invariant.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from matrixone_tpu.cdc import CdcTask, FileWatermark
from matrixone_tpu.container.dtypes import DType, TypeOid
from matrixone_tpu.logservice.replicated import ReplicaCore
from matrixone_tpu.storage.engine import ROWID, Engine, TableMeta
from matrixone_tpu.storage.fileservice import (MemoryFS,
                                               RecordingFileService)
from matrixone_tpu.utils.crash import CrashJournal

INT64 = DType(TypeOid.INT64)
VARCHAR = DType(TypeOid.VARCHAR, width=64)

#: plant flag (tools/mocrash/plants.py): persist the CDC watermark
#: BEFORE delivering to the mirror — the "watermark advanced before its
#: backing commit is durable" violation the sweep must catch
WM_EARLY = False

_STRINGS = ["ash", "birch", "cedar", "fir", "oak", "pine", "teak"]


@dataclasses.dataclass
class Ack:
    """One acknowledged operation: everything it did is journaled at
    indices < event_hi (recorded AFTER the call returned)."""
    op: str                 # insert|delete|txn2|ddl|snapshot|snapdrop|
    #                         mview|checkpoint|merge|gc|cdc_sync|
    #                         qappend|qtruncate
    event_lo: int           # journal position just before the op started
    event_hi: int           # journal position right after it returned
    table: str = ""
    ids: Tuple[int, ...] = ()
    rows: Dict[int, tuple] = dataclasses.field(default_factory=dict)
    pair_ids: Tuple[int, ...] = ()
    seq: int = 0            # quorum scenario
    payload: bytes = b""
    upto: int = 0
    ts: int = 0             # snapshot acks: the pinned timestamp


@dataclasses.dataclass
class EngineWorld:
    journal: CrashJournal
    acks: List[Ack]
    seed: int
    mirror_wm_path: str = "cdc/t_main.wm"

    # ---------------- expected-state folding (the checker's oracle)
    def fold(self, k: int):
        """State implied by the acks visible at crash point k:
        (expected t_main id->row, expected t_pair id set, ddl set,
        in-flight Ack or None).  Ops after the in-flight one never
        started — the workload is single-threaded."""
        main: Dict[int, tuple] = {}
        pair: set = set()
        ddl: set = set()
        inflight: Optional[Ack] = None
        for a in self.acks:
            if a.event_hi > k:
                inflight = a
                break
            if a.op == "insert":
                main.update(a.rows)
            elif a.op == "delete":
                for i in a.ids:
                    main.pop(i, None)
            elif a.op == "txn2":
                main.update(a.rows)
                pair.update(a.pair_ids)
            elif a.op in ("ddl", "snapshot", "mview"):
                ddl.add(a.table)
            elif a.op == "snapdrop":
                ddl.discard(a.table)
        return main, pair, ddl, inflight


@dataclasses.dataclass
class QuorumWorld:
    journal: CrashJournal
    acks: List[Ack]
    seed: int
    n_replicas: int = 3


class EngineSink:
    """CDC sink applying full DML to a second engine with PK upsert
    semantics — delete-then-insert in ONE commit, so a replayed event
    (at-least-once delivery) converges instead of duplicating."""

    def __init__(self, eng: Engine, table: str):
        self.eng = eng
        self.table = table

    def _gids_for(self, ids: List[int]) -> np.ndarray:
        t = self.eng.get_table(self.table)
        want = set(int(i) for i in ids)
        gids = []
        for arrays, _v, _d, n in t.iter_chunks(["id", ROWID], 1 << 20):
            for i in range(n):
                if int(arrays["id"][i]) in want:
                    gids.append(int(arrays[ROWID][i]))
        return np.asarray(gids, np.int64)

    def on_insert(self, table, rows, pk_cols=None):
        if not rows:
            return
        t = self.eng.get_table(self.table)
        n = len(rows)
        arrays = {
            "id": np.asarray([r["id"] for r in rows], np.int64),
            "batch": np.asarray([r["batch"] or 0 for r in rows],
                                np.int64),
            "v": np.asarray([r["v"] or 0 for r in rows], np.int64),
            "s": t.encode_strings_list("s", [r["s"] for r in rows]),
        }
        validity = {
            "id": np.ones(n, np.bool_),
            "batch": np.asarray([r["batch"] is not None for r in rows]),
            "v": np.asarray([r["v"] is not None for r in rows]),
            "s": np.asarray([r["s"] is not None for r in rows]),
        }
        gids = self._gids_for([r["id"] for r in rows])
        self.eng.commit_txn(
            None, {self.table: [(arrays, validity)]},
            {self.table: gids} if len(gids) else {})

    def on_delete(self, table, pk_rows):
        if not pk_rows:
            return
        gids = self._gids_for([r["id"] for r in pk_rows])
        if len(gids):
            self.eng.commit_txn(None, {}, {self.table: gids})


def _clear_table(eng: Engine, name: str) -> None:
    """Tombstone every visible row (one commit) — the mirror re-seed."""
    t = eng.get_table(name)
    gids: List[int] = []
    for arrays, _v, _d, n in t.iter_chunks([ROWID], 1 << 20):
        gids.extend(int(g) for g in arrays[ROWID])
    if gids:
        eng.commit_txn(None, {}, {name: np.asarray(gids, np.int64)})


def _main_meta() -> TableMeta:
    return TableMeta("t_main",
                     [("id", INT64), ("batch", INT64),
                      ("v", INT64), ("s", VARCHAR)],
                     ["id"])


def mirror_engine(fs) -> Engine:
    """A fresh (or reopened) mirror engine holding the t_main clone."""
    if fs.exists("meta/manifest.json") or fs.exists("wal/wal.log"):
        eng = Engine.open(fs)
    else:
        eng = Engine(fs)
    if "t_main" not in eng.tables:
        eng.create_table(_main_meta())
    return eng


def run_engine_workload(seed: int = 2026) -> EngineWorld:
    """Execute the seeded engine scenario; returns the shared journal +
    the ack log."""
    from matrixone_tpu.frontend import Session
    rng = np.random.default_rng(seed)
    journal = CrashJournal()
    fs = RecordingFileService(MemoryFS(), journal, "tn")
    mfs = RecordingFileService(MemoryFS(), journal, "mirror")
    eng = Engine(fs)
    sess = Session(catalog=eng)
    meng = mirror_engine(mfs)
    wm = FileWatermark(mfs, "cdc/t_main.wm")
    acks: List[Ack] = []
    next_id = [0]
    batch_no = [0]
    live: Dict[int, tuple] = {}

    def ack(op: str, lo: int, **kw) -> Ack:
        a = Ack(op=op, event_lo=lo, event_hi=journal.position(), **kw)
        acks.append(a)
        return a

    def insert_batch(n: int):
        batch_no[0] += 1
        b = batch_no[0]
        ids = list(range(next_id[0], next_id[0] + n))
        next_id[0] += n
        rows = {}
        vals = []
        for i in ids:
            v = int(rng.integers(0, 1000))
            s = (None if rng.random() < 0.15
                 else _STRINGS[int(rng.integers(len(_STRINGS)))])
            rows[i] = (b, v, s)
            vals.append(f"({i}, {b}, {v}, "
                        + ("null" if s is None else f"'{s}'") + ")")
        lo = journal.position()
        sess.execute("insert into t_main (id, batch, v, s) values "
                     + ", ".join(vals))
        live.update(rows)
        ack("insert", lo, table="t_main", ids=tuple(ids), rows=rows)

    def delete_some(k: int):
        if not live:
            return
        ids = sorted(live)
        pick = tuple(int(ids[j]) for j in
                     sorted(rng.choice(len(ids), size=min(k, len(ids)),
                                       replace=False)))
        lo = journal.position()
        sess.execute("delete from t_main where id in ("
                     + ", ".join(str(i) for i in pick) + ")")
        for i in pick:
            live.pop(i, None)
        ack("delete", lo, table="t_main", ids=pick)

    def cdc_sync():
        """Deliver everything past the durable watermark to the mirror,
        then persist the new watermark — AFTER the deliveries are
        durable (the plant flips the order)."""
        lo = journal.position()
        task = CdcTask(eng, "t_main", EngineSink(meng, "t_main"),
                       from_ts=wm.load())
        if WM_EARLY:
            # PLANTED VIOLATION: claim everything up to the current
            # frontier is delivered before delivering any of it
            wm.store(eng.committed_ts)
        try:
            task.backfill(from_ts=task.watermark)
        except ValueError:
            # a merge compacted deltas below the watermark: the
            # documented recovery — re-seed the mirror from scratch
            _clear_table(meng, "t_main")
            task.watermark = 0
            task.backfill(from_ts=0)
        if not WM_EARLY:
            wm.store(task.watermark)
        ack("cdc_sync", lo)

    # ---- the script
    lo = journal.position()
    sess.execute("create table t_main (id bigint primary key, "
                 "batch bigint, v bigint, s varchar(64))")
    ack("ddl", lo, table="t_main")
    lo = journal.position()
    sess.execute("create table t_pair (id bigint primary key, "
                 "src bigint)")
    ack("ddl", lo, table="t_pair")

    insert_batch(int(rng.integers(4, 9)))
    insert_batch(int(rng.integers(4, 9)))

    lo = journal.position()
    sess.execute("create materialized view mv1 as select s, sum(v) sv, "
                 "count(*) c from t_main group by s")
    ack("mview", lo, table="mv1")

    insert_batch(int(rng.integers(3, 7)))
    delete_some(2)
    cdc_sync()

    lo = journal.position()
    eng.create_snapshot("snap_wk")
    ack("snapshot", lo, table="snap_wk")

    lo = journal.position()
    sess.execute("select mo_ctl('checkpoint')")
    ack("checkpoint", lo)

    insert_batch(int(rng.integers(3, 7)))

    # multi-table atomic txn straight through the commit pipeline: both
    # tables' rows or neither (the commit frame is the atom)
    b = batch_no[0] = batch_no[0] + 1
    ids = list(range(next_id[0], next_id[0] + 3))
    next_id[0] += 3
    rows = {i: (b, i * 7, "teak") for i in ids}
    t_main = eng.get_table("t_main")
    arrays = {"id": np.asarray(ids, np.int64),
              "batch": np.full(3, b, np.int64),
              "v": np.asarray([i * 7 for i in ids], np.int64),
              "s": t_main.encode_strings_list("s", ["teak"] * 3)}
    ones = np.ones(3, np.bool_)
    validity = {c: ones.copy() for c in ("id", "batch", "v", "s")}
    pair = {"id": np.asarray(ids, np.int64),
            "src": np.asarray(ids, np.int64)}
    pvalid = {c: ones.copy() for c in ("id", "src")}
    lo = journal.position()
    eng.commit_txn(None, {"t_main": [(arrays, validity)],
                          "t_pair": [(pair, pvalid)]}, {})
    live.update(rows)
    ack("txn2", lo, table="t_main", ids=tuple(ids), rows=rows,
        pair_ids=tuple(ids))

    delete_some(1)
    cdc_sync()

    lo = journal.position()
    sess.execute("select mo_ctl('merge', 't_main')")
    ack("merge", lo)

    insert_batch(int(rng.integers(3, 6)))
    cdc_sync()

    sess.close()
    return EngineWorld(journal=journal, acks=acks, seed=seed)


def run_merge_workload(seed: int = 2026) -> EngineWorld:
    """Merge-under-traffic scenario: MergeScheduler cycles (candidate
    pick -> off-lock rewrite -> catalog swap -> fence GC -> checkpoint)
    interleave with foreground commits, a pinned named snapshot, and
    CDC fenced resumes — so the sweep crashes at every scheduler
    decision point and checks acked data survives, AS OF reads stay
    exact across the swap, deltas replay exactly-once, and no object is
    GC'd while a snapshot or fence can still reach it."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.merge_sched import MergeScheduler
    rng = np.random.default_rng(seed)
    journal = CrashJournal()
    fs = RecordingFileService(MemoryFS(), journal, "tn")
    mfs = RecordingFileService(MemoryFS(), journal, "mirror")
    eng = Engine(fs)
    sess = Session(catalog=eng)
    meng = mirror_engine(mfs)
    wm = FileWatermark(mfs, "cdc/t_main.wm")
    sched = MergeScheduler(eng)
    sched.min_segments = 2           # small history: compact eagerly
    acks: List[Ack] = []
    next_id = [0]
    batch_no = [0]
    live: Dict[int, tuple] = {}

    def ack(op: str, lo: int, **kw) -> Ack:
        a = Ack(op=op, event_lo=lo, event_hi=journal.position(), **kw)
        acks.append(a)
        return a

    def insert_batch(n: int):
        batch_no[0] += 1
        b = batch_no[0]
        ids = list(range(next_id[0], next_id[0] + n))
        next_id[0] += n
        rows = {}
        vals = []
        for i in ids:
            v = int(rng.integers(0, 1000))
            s = (None if rng.random() < 0.15
                 else _STRINGS[int(rng.integers(len(_STRINGS)))])
            rows[i] = (b, v, s)
            vals.append(f"({i}, {b}, {v}, "
                        + ("null" if s is None else f"'{s}'") + ")")
        lo = journal.position()
        sess.execute("insert into t_main (id, batch, v, s) values "
                     + ", ".join(vals))
        live.update(rows)
        ack("insert", lo, table="t_main", ids=tuple(ids), rows=rows)

    def delete_some(k: int):
        if not live:
            return
        ids = sorted(live)
        pick = tuple(int(ids[j]) for j in
                     sorted(rng.choice(len(ids), size=min(k, len(ids)),
                                       replace=False)))
        lo = journal.position()
        sess.execute("delete from t_main where id in ("
                     + ", ".join(str(i) for i in pick) + ")")
        for i in pick:
            live.pop(i, None)
        ack("delete", lo, table="t_main", ids=pick)

    def cdc_sync():
        """Resume the mirror from its durable watermark.  Below a held
        fence this is the exactly-once fenced catch-up; only when the
        fence was GC'd (floor above the watermark) does the documented
        degrade rung re-seed from scratch."""
        lo = journal.position()
        task = CdcTask(eng, "t_main", EngineSink(meng, "t_main"),
                       from_ts=wm.load())
        try:
            task.backfill(from_ts=task.watermark)
        except ValueError:
            _clear_table(meng, "t_main")
            task.watermark = 0
            task.backfill(from_ts=0)
        wm.store(task.watermark)
        ack("cdc_sync", lo)

    def merge_cycle(op: str):
        lo = journal.position()
        sched.run_cycle()       # merge + fence GC + checkpoint cadence
        ack(op, lo)

    # ---- the script
    lo = journal.position()
    sess.execute("create table t_main (id bigint primary key, "
                 "batch bigint, v bigint, s varchar(64))")
    ack("ddl", lo, table="t_main")

    insert_batch(int(rng.integers(4, 8)))
    lo = journal.position()
    sess.execute("create materialized view mv1 as select s, sum(v) sv, "
                 "count(*) c from t_main group by s")
    ack("mview", lo, table="mv1")
    insert_batch(int(rng.integers(3, 7)))
    delete_some(2)
    cdc_sync()
    insert_batch(int(rng.integers(3, 6)))

    # pin the pre-merge history with a named snapshot, remembering
    # exactly what an AS OF read of it must return forever after
    lo = journal.position()
    snap_ts = eng.create_snapshot("snap_mg")
    ack("snapshot", lo, table="snap_mg", rows=dict(live), ts=snap_ts)

    lo = journal.position()
    sess.execute("select mo_ctl('checkpoint')")
    ack("checkpoint", lo)      # pre-merge segments now object-backed

    insert_batch(int(rng.integers(3, 6)))
    delete_some(2)

    # scheduler cycle 1: compacts below BOTH the snapshot and the CDC
    # watermark — the fence pins the pre-merge view, GC must hold
    merge_cycle("merge")

    insert_batch(int(rng.integers(3, 6)))
    cdc_sync()                 # fenced resume: watermark < merge_ts
    delete_some(1)
    insert_batch(int(rng.integers(2, 5)))

    # scheduler cycle 2: a second merge stacks a second fence
    merge_cycle("merge")
    cdc_sync()

    # release: drop the pin — the next cycle's gc_fences releases the
    # fences (manifest durable FIRST) and deletes the pre-merge objects
    lo = journal.position()
    eng.drop_snapshot("snap_mg")
    ack("snapdrop", lo, table="snap_mg")
    merge_cycle("gc")

    insert_batch(int(rng.integers(2, 5)))
    cdc_sync()
    sess.close()
    return EngineWorld(journal=journal, acks=acks, seed=seed)


def run_quorum_workload(seed: int = 2026,
                        n_entries: int = 10) -> QuorumWorld:
    """Majority-ack append stream over three recorded replica cores,
    with one mid-stream checkpoint truncation — the ReplicatedLog
    durability contract at disk granularity."""
    rng = np.random.default_rng(seed)
    journal = CrashJournal()
    cores = [ReplicaCore(RecordingFileService(MemoryFS(), journal,
                                              f"rep{i}"))
             for i in range(3)]
    acks: List[Ack] = []
    epoch = 1
    for seq in range(1, n_entries + 1):
        payload = (f"entry-{seq}-".encode()
                   * int(1 + rng.integers(1, 4)))
        # one replica is occasionally "down" — a majority still acks
        skip = int(rng.integers(0, 3)) if rng.random() < 0.3 else -1
        lo = journal.position()
        ok = 0
        for i, c in enumerate(cores):
            if i == skip:
                continue
            if c.append(epoch, seq, payload).get("ok"):
                ok += 1
        if ok >= 2:
            acks.append(Ack(op="qappend", event_lo=lo,
                            event_hi=journal.position(), seq=seq,
                            payload=payload))
        if seq == n_entries // 2:
            upto = seq - 1
            lo = journal.position()
            for c in cores:
                c.truncate(epoch, upto)
            acks.append(Ack(op="qtruncate", event_lo=lo,
                            event_hi=journal.position(), upto=upto))
    return QuorumWorld(journal=journal, acks=acks, seed=seed)

"""mokey — trace-capture / cache-key completeness analyzer (static
half; the runtime half is matrixone_tpu/utils/keys.py).

The engine caches compiled JAX programs in four places — fragment
programs (vm/fusion.py + the join/window subclasses), UDF bodies
(udf/executor.py), mview delta programs (mview/maintain.py) and
compiled operator trees (serving/plan_cache.py) — and its #1 historical
bug class is a cached program whose traced closure captured something
the cache key did not cover: the PR-7 dictionary LUT keyed by LENGTH
instead of content, the PR-13 build key missing its lifted-literal
arity.  Each shipped plausible-but-wrong rows and was found late.

This pass makes the class visible at lint time.  Over every module
that touches a recognized compile cache it:

  1. discovers the TRACE ROOTS molint's jit-purity checker also
     discovers — `@jax.jit` defs, `jax.jit(f)` wrap targets through
     local aliases, and factory-returned closures (plus closures a
     root CAPTURES from a factory, e.g. the shared `chain` body);
  2. computes what each traced closure CAPTURES: free variables from
     enclosing function scopes and `self.`-attribute reads;
  3. resolves every capture to one of
       (a) a traced argument        — parameters are traced by
                                      construction, so free vars are
                                      exactly the non-(a) set;
       (b) a compile-key component  — the name (or what it was
                                      assigned from, chased through
                                      local dataflow) appears in the
                                      KEY VOCABULARY: the backward
                                      closure of names feeding the
                                      key expression at the cache
                                      access, through key-builder
                                      methods and `self.x = ...`
                                      assignments across related
                                      classes;
       (c) a runtime-audited dep    — the name appears in the
                                      checked-in handshake export
                                      (observed_captures.json) the
                                      armed auditor wrote for this
                                      module (the mosan
                                      observed-edges pattern);
       (d) a declared invariant     — `# mokey: invariant=<name> --
                                      <justification>` inside the
                                      enclosing factory;
     anything else is a `key-capture` finding.  A capture whose only
     path into the key goes through `len()`/`id()` is the PR-7 shape
     and reports as `weak-key` even though the name technically
     appears.

The vocabulary chase is a deliberate over-approximation (bare-name
method dispatch, whole-body inlining of key builders): mokey's job is
zero FALSE findings on a disciplined tree while the two historical bug
shapes stay mechanically detectable — the runtime auditor is the sound
content oracle, and the fixture pairs under tests/mokey_fixtures pin
both sides.  Gate: tests/test_mokey.py::test_repo_tree_is_clean.

CLI: `python -m tools.mokey [paths] [--json]`; programmatic surface
`run_checks(root)` / `last_run_status()` mirrors tools/molint.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
import time
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.molint import Finding, Project, PyModule, repo_root
from tools.molint.astutil import dotted
from tools.molint.checkers.jit_purity import (_decorated_as_jit,
                                              _jit_wrap_target)

#: receivers whose .entry/.lookup/... calls count as compile-cache
#: accesses (terminal attribute or full dotted name, case-insensitive)
_CACHE_RECV_RE = re.compile(
    r"(?i)(cache|progs?|programs|entries|_lru|compiled)")
_CACHE_METHODS = {"entry", "lookup", "insert", "get", "setdefault",
                  "peek", "take_tree", "put_tree"}

_DECL_RE = re.compile(
    r"#\s*mokey:\s*invariant\s*=\s*(?P<names>[A-Za-z0-9_.,]+)"
    r"\s*(?P<rest>.*)$")
_JUST_STRIP = re.compile(r"^[\s:;—-]+")

#: default handshake export (written by MO_KEY_EXPORT=1 test runs)
OBSERVED_DEFAULT = os.path.join(os.path.dirname(__file__),
                                "observed_captures.json")

import builtins as _b

_BUILTINS = set(dir(_b))

_MAX_DEPTH = 5                  # dataflow recursion bound
_MAX_VOCAB = 4000               # vocabulary expansion budget


# =====================================================================
# per-module structure
# =====================================================================

class _FuncRec:
    """One function/method with its lexical position."""

    __slots__ = ("node", "name", "classname", "parents", "module")

    def __init__(self, node, name, classname, parents, module):
        self.node = node
        self.name = name
        self.classname = classname      # enclosing class or None
        self.parents = parents          # enclosing FunctionDefs, outer->inner
        self.module = module

    @property
    def span(self) -> Tuple[int, int]:
        return (self.node.lineno,
                getattr(self.node, "end_lineno", self.node.lineno))


class _Decl:
    """One `# mokey: invariant=a,b -- why` declaration."""

    __slots__ = ("lineno", "names", "justification", "used")

    def __init__(self, lineno, names, justification):
        self.lineno = lineno
        self.names = names
        self.justification = justification
        self.used = False


class _ModIndex:
    """Everything the analyzer needs from one parsed module."""

    def __init__(self, mod: PyModule):
        self.mod = mod
        self.funcs: List[_FuncRec] = []
        self.by_name: Dict[str, List[_FuncRec]] = {}
        self.module_bindings: Set[str] = set()
        self.class_bases: Dict[str, List[str]] = {}
        self.decls: List[_Decl] = []
        self._attr_assigns: Optional[Dict[str, list]] = None
        if mod.tree is None:
            return
        for node in mod.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    self.module_bindings.add(
                        (a.asname or a.name).split(".")[0])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                self.module_bindings.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_bindings.add(t.id)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                self.module_bindings.add(node.target.id)
        self._walk(mod.tree, None, [])
        for fr in self.funcs:
            self.by_name.setdefault(fr.name, []).append(fr)
        for i, line in enumerate(mod.lines, 1):
            m = _DECL_RE.search(line)
            if not m:
                continue
            names = [n.strip() for n in m.group("names").split(",")
                     if n.strip()]
            just = _JUST_STRIP.sub("", m.group("rest")).strip()
            self.decls.append(_Decl(i, names, just))

    def attr_assigns(self) -> Dict[str, list]:
        """attr name -> [(RHS, method _FuncRec)] for every
        `self.<attr> = ...` in the module (built once — the resolver
        and vocabulary chase query this constantly)."""
        if self._attr_assigns is None:
            out: Dict[str, list] = {}
            for fr in self.funcs:
                if fr.classname is None:
                    continue
                for node in ast.walk(fr.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        ch = _self_chain(t) \
                            if isinstance(t, ast.Attribute) else None
                        if ch is not None and ch.count(".") == 1:
                            out.setdefault(ch.split(".")[1],
                                           []).append((node.value, fr))
            self._attr_assigns = out
        return self._attr_assigns

    def _walk(self, node, classname, parents):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self.funcs.append(_FuncRec(child, child.name, classname,
                                           list(parents), self.mod))
                self._walk(child, classname, parents + [child])
            elif isinstance(child, ast.ClassDef):
                self.class_bases[child.name] = [
                    b for b in (dotted(x) for x in child.bases) if b]
                self._walk(child, child.name, parents)
            else:
                self._walk(child, classname, parents)


# =====================================================================
# expression item extraction (names + self chains, len/id weakness)
# =====================================================================

def _self_chain(node) -> Optional[str]:
    """'self.a.b' (up to 3 attrs) for an Attribute chain on self."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return "self." + ".".join(reversed(parts[-3:]))
    return None


def _expr_items(node) -> List[Tuple[str, bool, Optional[ast.Call]]]:
    """(item, strong, call) for every name / self-chain / call in an
    expression.  `strong` is False when the occurrence sits directly
    inside `len(...)` / `id(...)` — the PR-7 length-only-key shape.
    Calls are returned so the caller can chase key-builder methods."""
    out: List[Tuple[str, bool, Optional[ast.Call]]] = []

    def visit(n, weak, bound):
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            inner = set(bound)
            for gen in n.generators:
                for t in ast.walk(gen.target):
                    if isinstance(t, ast.Name):
                        inner.add(t.id)
                visit(gen.iter, weak, bound)
                for cond in gen.ifs:
                    visit(cond, weak, inner)
            if isinstance(n, ast.DictComp):
                visit(n.key, weak, inner)
                visit(n.value, weak, inner)
            else:
                visit(n.elt, weak, inner)
            return
        if isinstance(n, ast.Lambda):
            inner = set(bound) | {a.arg for a in
                                  (n.args.posonlyargs + n.args.args
                                   + n.args.kwonlyargs)}
            visit(n.body, weak, inner)
            return
        if isinstance(n, ast.Call):
            fn = n.func
            fname = dotted(fn)
            inner_weak = weak
            if isinstance(fn, ast.Name) and fn.id in ("len", "id"):
                inner_weak = True
            else:
                out.append(((fname or "?call"), not weak, n))
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                visit(a, inner_weak, bound)
            if not isinstance(fn, ast.Name) and dotted(fn) is None \
                    and _self_chain(fn) is None:
                # complex callee (subscript/call result): its parts are
                # data, not a method identity already on the call item
                visit(fn, weak, bound)
            return
        if isinstance(n, ast.Attribute):
            ch = _self_chain(n)
            if ch is not None:
                out.append((ch, not weak, None))
                return
            d = dotted(n)
            if d is not None:
                if d.split(".")[0] not in bound:
                    out.append((d.split(".")[0], not weak, None))
                return
        if isinstance(n, ast.Name):
            if n.id not in bound:
                out.append((n.id, not weak, None))
            return
        for c in ast.iter_child_nodes(n):
            visit(c, weak, bound)

    visit(node, False, set())
    return out


def _target_names(t) -> List[str]:
    """Bare names bound by one assignment target (tuple unpacking
    included — `fn, fieldmap = ...` binds both to the whole RHS)."""
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _assignments_to(fn_node, name: str, skip: Optional[ast.AST] = None
                    ) -> List[ast.AST]:
    """RHS expressions assigned to bare `name` within fn_node's body
    (nested defs other than `skip` excluded — their locals shadow;
    tuple-unpack targets over-approximate to the whole RHS)."""
    out = []

    def visit(n):
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and c is not skip:
                continue
            if isinstance(c, ast.Assign):
                for t in c.targets:
                    if name in _target_names(t):
                        out.append(c.value)
            elif isinstance(c, (ast.AugAssign, ast.AnnAssign)) and \
                    isinstance(c.target, ast.Name) and \
                    c.target.id == name and c.value is not None:
                out.append(c.value)
            elif isinstance(c, ast.For) and \
                    name in _target_names(c.target):
                out.append(c.iter)
            visit(c)

    visit(fn_node)
    return out


def _attr_assignments(indexes: Dict[str, "_ModIndex"], relatives,
                      chain: str
                      ) -> List[Tuple[ast.AST, "_FuncRec", "_ModIndex"]]:
    """(RHS, containing method, module) for every `self.x = ...` of
    chain 'self.x' across the related classes (any module)."""
    head = chain.split(".")[1] if chain.startswith("self.") else chain
    out = []
    for idx in indexes.values():
        for rhs, fr in idx.attr_assigns().get(head, ()):
            if fr.classname in relatives:
                out.append((rhs, fr, idx))
    return out


# =====================================================================
# class relations (name-matched across the project, jit-purity policy)
# =====================================================================

def _related_classes(indexes: Dict[str, "_ModIndex"],
                     classname: Optional[str]) -> Set[str]:
    if classname is None:
        return set()
    bases: Dict[str, Set[str]] = {}
    for idx in indexes.values():
        for cls, bs in idx.class_bases.items():
            bases.setdefault(cls, set()).update(
                b.split(".")[-1] for b in bs)
    rel = {classname}
    while True:
        more = set()
        for cls, bs in bases.items():
            if cls in rel and bs - rel:
                more |= bs - rel            # ancestors
            if bs & rel and cls not in rel:
                more.add(cls)               # descendants
        if not more:
            break
        rel |= more
    return rel


# =====================================================================
# key vocabulary
# =====================================================================

class _Vocab:
    __slots__ = ("strong", "weak", "sites")

    def __init__(self):
        self.strong: Set[str] = set()
        self.weak: Set[str] = set()
        self.sites: List[Tuple[str, int]] = []   # (path, lineno)

    def has(self, item: str) -> bool:
        return self._match(item, self.strong)

    def has_weak(self, item: str) -> bool:
        return self._match(item, self.weak)

    @staticmethod
    def _match(item: str, pool: Set[str]) -> bool:
        if item in pool:
            return True
        if item.startswith("self."):
            # prefix match: vocab 'self._agg_op' covers capture
            # 'self._agg_op.node' (an attribute of a keyed object)
            parts = item.split(".")
            for i in range(2, len(parts)):
                if ".".join(parts[:i]) in pool:
                    return True
            # and the tail as a bare name ('_lift_lits' via param)
            return parts[-1] in pool
        return False


def _cache_call_sites(idx: _ModIndex):
    """(call, key_expr, enclosing _FuncRec) for every recognized
    compile-cache access in the module."""
    out = []
    for fr in idx.funcs:
        for node in ast.walk(fr.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CACHE_METHODS
                    and node.args):
                continue
            recv = dotted(node.func.value) or (
                _self_chain(node.func.value) or "")
            if not _CACHE_RECV_RE.search(recv):
                continue
            # innermost enclosing function wins
            best = None
            for cand in idx.funcs:
                s, e = cand.span
                if s <= node.lineno <= e and (
                        best is None or s >= best.span[0]):
                    best = cand
            if best is not None:
                out.append((node, node.args[0], best))
    return out


def _build_vocab(indexes: Dict[str, "_ModIndex"], idx: _ModIndex,
                 scope_classes: Set[str]) -> _Vocab:
    """The key vocabulary for a class scope (or the module when
    scope_classes is empty): the backward closure of names feeding any
    cache-key expression of the scope, through local assignments,
    key-builder method bodies (bare-name dispatch across related
    classes), and `self.x = ...` provenance."""
    vocab = _Vocab()
    #: worklist of (kind, payload): ("expr", node, fn_node, modidx,
    #: ctx_weak), ("method", name, modidx, ctx_weak).  ctx_weak marks
    #: provenance chased out of a len()/id()-only occurrence — its
    #: constituents must land in the WEAK pool too, or a length-only
    #: key would launder its dictionary into the strong vocabulary
    work: list = []
    seen_expr: Set[tuple] = set()
    seen_methods: Set[tuple] = set()

    def add_expr(node, fn_node, modidx, ctx_weak):
        k = (id(node), ctx_weak)
        if k in seen_expr:
            return
        seen_expr.add(k)
        work.append(("expr", node, fn_node, modidx, ctx_weak))

    for midx in indexes.values():
        for call, key_expr, fr in _cache_call_sites(midx):
            in_scope = (fr.classname in scope_classes if scope_classes
                        else (midx is idx and fr.classname is None))
            if not in_scope:
                continue
            vocab.sites.append((midx.mod.path, call.lineno))
            add_expr(key_expr, fr.node, midx, False)

    budget = _MAX_VOCAB
    while work and budget > 0:
        budget -= 1
        kind, *payload = work.pop()
        if kind == "method":
            name, midx, ctx_weak = payload
            for owner in indexes.values():
                for fr2 in owner.by_name.get(name, ()):
                    if fr2.classname is not None and scope_classes and \
                            fr2.classname not in scope_classes:
                        continue
                    add_expr(fr2.node, fr2.node, owner, ctx_weak)
            continue
        node, fn_node, midx, ctx_weak = payload
        for item, strong, call in _expr_items(node):
            eff_strong = strong and not ctx_weak
            pool = vocab.strong if eff_strong else vocab.weak
            if item == "?call":
                continue
            if call is not None:
                # a call in key position: its ARGUMENTS already visited
                # by _expr_items; chase the callee's body when it is a
                # method/function of this scope ("self._runtime_key",
                # "FF._dict_key", bare "helper")
                mname = item.split(".")[-1]
                mk = (mname, not eff_strong)
                if mk not in seen_methods:
                    seen_methods.add(mk)
                    work.append(("method", mname, midx,
                                 not eff_strong))
                continue
            if item in pool:
                continue
            pool.add(item)
            if item.startswith("self."):
                for rhs, owner_fr, owner in _attr_assignments(
                        indexes, scope_classes or {None}, item):
                    add_expr(rhs, owner_fr.node, owner,
                             not eff_strong)
            else:
                for rhs in _assignments_to(fn_node, item):
                    add_expr(rhs, fn_node, midx, not eff_strong)
    return vocab


# =====================================================================
# trace-root discovery
# =====================================================================

def _nested_defs(indexes: Dict[str, _ModIndex], fac_name: str
                 ) -> List[_FuncRec]:
    """Nested defs of every function named `fac_name` across the
    project (bare-name virtual dispatch — the jit-purity policy: a
    base-class wrap site reaches subclass factory overrides in other
    modules, e.g. fusion.py wrapping fusion_window's _make_step)."""
    out = []
    for owner in indexes.values():
        for fr in owner.by_name.get(fac_name, ()):
            for sub in owner.funcs:
                if sub.parents and sub.parents[-1] is fr.node:
                    out.append(sub)
    return out


def _jit_roots(indexes: Dict[str, _ModIndex], idx: _ModIndex
               ) -> List[_FuncRec]:
    """Defs traced by jax.jit/shard_map: decorated defs, wrap targets
    resolved through local aliases, factory-returned nested defs (the
    jit-purity discovery, on def nodes; factories dispatch by bare
    name across modules)."""
    if idx.mod.tree is None:
        return []
    roots: List[_FuncRec] = []
    seen: Set[int] = set()

    def add(fr: _FuncRec):
        if id(fr.node) not in seen:
            seen.add(id(fr.node))
            roots.append(fr)

    for fr in idx.funcs:
        if _decorated_as_jit(fr.node):
            add(fr)
    # alias and factory maps, module-wide (the jit_purity policy);
    # tuple-unpack targets (`fn, fieldmap = self._make_dense_step(...)`)
    # bind every name to the factory
    alias: Dict[str, Set[str]] = {}
    factory: Dict[str, Set[str]] = {}
    targets: List[str] = []
    for node in ast.walk(idx.mod.tree):
        if isinstance(node, ast.Assign):
            names = []
            for t in node.targets:
                names.extend(_target_names(t))
            v = node.value
            if isinstance(v, (ast.Name, ast.Attribute)):
                d = dotted(v) or _self_chain(v)
                if d:
                    for nm in names:
                        alias.setdefault(nm, set()).add(
                            d.split(".")[-1])
            elif isinstance(v, ast.Call):
                d = dotted(v.func) or _self_chain(v.func)
                if d:
                    for nm in names:
                        factory.setdefault(nm, set()).add(
                            d.split(".")[-1])
        if isinstance(node, ast.Call):
            tgt = _jit_wrap_target(node)
            if tgt:
                targets.append(tgt)
    for tgt in targets:
        names = {tgt}
        while True:
            more = {a for n in names for a in alias.get(n, ())} - names
            if not more:
                break
            names |= more
        for n in names:
            for fr in idx.by_name.get(n, ()):
                add(fr)
        for fac in {f for n in names for f in factory.get(n, ())}:
            for sub in _nested_defs(indexes, fac):
                add(sub)
    return roots


# =====================================================================
# capture computation
# =====================================================================

def _captures_of(fr: _FuncRec, idx: _ModIndex
                 ) -> List[Tuple[str, int]]:
    """(capture item, first line) for a traced def: free bare names
    bound in an enclosing function scope, plus self-attribute chains.
    Walks the WHOLE subtree (nested helper defs run at trace time
    too)."""
    node = fr.node
    bound: Set[str] = set()
    loads: Dict[str, int] = {}
    self_chains: Dict[str, int] = {}

    def collect_args(args):
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            bound.add(a.arg)
        if args.vararg:
            bound.add(args.vararg.arg)
        if args.kwarg:
            bound.add(args.kwarg.arg)

    collect_args(node.args)
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(sub.name)
            if sub is not node:
                collect_args(sub.args)
        elif isinstance(sub, ast.Lambda):
            collect_args(sub.args)
        elif isinstance(sub, ast.Name):
            if isinstance(sub.ctx, (ast.Store, ast.Del)):
                bound.add(sub.id)
            elif sub.id not in loads:
                loads[sub.id] = sub.lineno
        elif isinstance(sub, ast.Attribute) and \
                isinstance(sub.ctx, ast.Load):
            ch = _self_chain(sub)
            if ch is not None and ch not in self_chains:
                self_chains[ch] = sub.lineno
        elif isinstance(sub, (ast.Global, ast.Nonlocal)):
            for n in sub.names:
                bound.discard(n)
        elif isinstance(sub, ast.comprehension):
            for t in ast.walk(sub.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(sub, (ast.ExceptHandler,)) and sub.name:
            bound.add(sub.name)
        elif isinstance(sub, ast.withitem) and sub.optional_vars:
            for t in ast.walk(sub.optional_vars):
                if isinstance(t, ast.Name):
                    bound.add(t.id)

    enclosing_bound: Set[str] = set()
    for p in fr.parents:
        a = p.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            enclosing_bound.add(arg.arg)
        if a.vararg:
            enclosing_bound.add(a.vararg.arg)
        if a.kwarg:
            enclosing_bound.add(a.kwarg.arg)
        for sub in ast.walk(p):
            if isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Store):
                enclosing_bound.add(sub.id)
            elif isinstance(sub, ast.comprehension):
                for t in ast.walk(sub.target):
                    if isinstance(t, ast.Name):
                        enclosing_bound.add(t.id)

    out: List[Tuple[str, int]] = []
    for name, line in sorted(loads.items()):
        if name in bound or name in _BUILTINS or name == "self":
            continue
        if name in idx.module_bindings:
            continue                        # (a)-adjacent: module code
        if name not in enclosing_bound:
            continue                        # not a closure capture
        out.append((name, line))
    for ch, line in sorted(self_chains.items()):
        out.append((ch, line))
    return out


# =====================================================================
# resolution
# =====================================================================

class _Ctx:
    """One resolution context: the function scopes whose assignments a
    name may come from, plus the owning module."""

    __slots__ = ("parents", "idx", "skip")

    def __init__(self, parents, idx: _ModIndex, skip=None):
        self.parents = parents          # fn nodes, outer -> inner
        self.idx = idx
        self.skip = skip                # the closure itself (excluded)

    def key(self) -> int:
        return id(self.parents[-1]) if self.parents else id(self.idx)


def _params_of(fn_node) -> List[str]:
    a = fn_node.args
    return [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)]


def _enclosing_func(idx: _ModIndex, lineno: int) -> Optional[_FuncRec]:
    best = None
    for fr in idx.funcs:
        s, e = fr.span
        if s <= lineno <= e and (best is None or s >= best.span[0]):
            best = fr
    return best


class _Resolver:
    def __init__(self, indexes: Dict[str, _ModIndex],
                 relatives: Set[str], vocab: _Vocab,
                 observed: Set[str]):
        self.indexes = indexes
        self.relatives = relatives
        self.vocab = vocab
        self.observed = observed
        #: factories whose nested defs must also be analyzed (the
        #: `chain = self._make_chain_fn(...)` shape)
        self.derived_factories: Set[str] = set()

    def resolve(self, ctx: _Ctx, item: str, depth: int = 0,
                visited: Optional[Set[tuple]] = None) -> str:
        """-> 'ok' | 'weak' | 'no' for one capture item in context."""
        if visited is None:
            visited = set()
        vk = (ctx.key(), item)
        if vk in visited or depth > _MAX_DEPTH:
            return "no"
        visited.add(vk)
        if item in self.observed or (
                item.startswith("self.")
                and item.split(".")[1] in self.observed):
            return "ok"
        if self.vocab.has(item):
            return "ok"
        weak_fallback = (lambda got:
                         "weak" if got == "no"
                         and self.vocab.has_weak(item) else got)
        if item.startswith("self."):
            tail = item.split(".")[1]
            for owner in self.indexes.values():
                for fr2 in owner.by_name.get(tail, ()):
                    if fr2.classname in self.relatives:
                        return "ok"      # a method reference: code,
                                         # not captured data
            rhss = _attr_assignments(self.indexes, self.relatives, item)
            if rhss:
                return weak_fallback(self._resolve_rhss(
                    [(r, _Ctx(fr2.parents + [fr2.node], owner))
                     for r, fr2, owner in rhss], depth, visited))
            return "weak" if self.vocab.has_weak(item) else "no"
        if item in ctx.idx.module_bindings or item in _BUILTINS:
            return "ok"
        # local dataflow: chase assignments in the context scopes
        rhss = []
        for p in ctx.parents:
            for r in _assignments_to(p, item, skip=ctx.skip):
                rhss.append((r, ctx))
        if rhss:
            return weak_fallback(self._resolve_rhss(rhss, depth,
                                                    visited))
        # a parameter of a context scope: resolve the matching ARGUMENT
        # expression at every call site of that function (the factory-
        # argument hop: `self._make_step(trig, ...)` keys `trig_schema`
        # through the caller's `trig`)
        got = self._via_call_sites(ctx, item, depth, visited)
        if got is not None:
            return got
        return "weak" if self.vocab.has_weak(item) else "no"

    def _via_call_sites(self, ctx: _Ctx, item: str, depth, visited
                        ) -> Optional[str]:
        owner_fn = None
        for p in ctx.parents:
            if item in _params_of(p):
                owner_fn = p
        if owner_fn is None:
            return None
        params = _params_of(owner_fn)
        pos = params.index(item)
        is_method = bool(params) and params[0] == "self"
        fname = owner_fn.name
        sites: List[Tuple[ast.AST, _Ctx]] = []
        for owner in self.indexes.values():
            if fname not in owner.mod.text:
                continue
            for fr2 in owner.funcs:
                for node in ast.walk(fr2.node):
                    if not isinstance(node, ast.Call):
                        continue
                    d = dotted(node.func) or _self_chain(node.func)
                    if not d or d.split(".")[-1] != fname:
                        continue
                    arg = None
                    ppos = pos - 1 if (is_method
                                       and not isinstance(node.func,
                                                          ast.Name)) \
                        else pos
                    if 0 <= ppos < len(node.args):
                        arg = node.args[ppos]
                    for kw in node.keywords:
                        if kw.arg == item:
                            arg = kw.value
                    if arg is None:
                        continue
                    caller = _enclosing_func(owner, node.lineno)
                    if caller is None:
                        continue
                    sites.append((arg, _Ctx(caller.parents
                                            + [caller.node], owner)))
        if not sites:
            return None
        return self._resolve_rhss(sites, depth, visited)

    def _resolve_rhss(self, rhss: List[Tuple[ast.AST, _Ctx]], depth,
                      visited) -> str:
        """A capture with reaching assignments/arguments resolves when
        EVERY constituent of EVERY reaching expression resolves (over-
        approximation of which one reaches the closure)."""
        worst = "ok"
        for rhs, ctx in rhss:
            for item2, strong, call in _expr_items(rhs):
                if item2 == "?call":
                    continue
                if call is not None:
                    # method/function code is module code; its nested
                    # defs become analysis roots (shared step bodies)
                    self.derived_factories.add(item2.split(".")[-1])
                    continue
                got = self.resolve(ctx, item2, depth + 1, set(visited))
                if got == "no":
                    return "no"
                if got == "weak":
                    worst = "weak"
        return worst


# =====================================================================
# declarations
# =====================================================================

def _decl_matches(decl: _Decl, item: str) -> bool:
    tail = item.split(".")[-1]
    for n in decl.names:
        ntail = n.split(".")[-1]
        if n == item or ntail == tail:
            return True
    return False


def _decl_for(idx: _ModIndex, fr: _FuncRec, item: str
              ) -> Optional[_Decl]:
    """A declaration covering `item`, scoped to the root's outermost
    enclosing factory span (or the whole module for module-level
    roots)."""
    if fr.parents:
        outer = fr.parents[0]
        lo, hi = outer.lineno, getattr(outer, "end_lineno",
                                       outer.lineno)
    else:
        lo, hi = 1, len(idx.mod.lines)
    for d in idx.decls:
        if lo <= d.lineno <= hi and _decl_matches(d, item):
            return d
    return None


# =====================================================================
# the analyzer
# =====================================================================

def load_observed(path: Optional[str] = None) -> Dict[str, Set[str]]:
    """site-path-suffix -> dep names from the handshake export.  A
    missing or unreadable export degrades to empty — never a crashed
    gate (the mosan convention)."""
    path = path or OBSERVED_DEFAULT
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        out: Dict[str, Set[str]] = {}
        for site, names in data.get("sites", {}).items():
            mod_path = site.rsplit(":", 1)[0]
            out.setdefault(mod_path, set()).update(names)
        return out
    except (OSError, ValueError):
        return {}


def run_checks(root: str, src_paths: Optional[List[str]] = None,
               observed_path: Optional[str] = None,
               record: bool = True):
    """Run the capture-completeness pass.  Scans <root>/matrixone_tpu
    by default; returns (findings, stats) in the molint shape."""
    global LAST_RUN
    t0 = time.perf_counter()
    root = os.path.abspath(root)
    if src_paths is None:
        src_paths = [os.path.join(root, "matrixone_tpu")]
    project = Project(root, src_paths, tests_dir=None, complete=False)
    observed_all = load_observed(observed_path)

    indexes: Dict[str, _ModIndex] = {}
    for mod in project.modules:
        if mod.tree is not None:
            indexes[mod.path] = _ModIndex(mod)

    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None:
            findings.append(Finding("parse", mod.path, 1,
                                    f"file does not parse: "
                                    f"{mod.parse_error}"))

    # ---- global root set (a wrap site in one module can root a
    # factory-returned closure defined in another)
    pending: List[_FuncRec] = []
    queued: Set[int] = set()
    for path in sorted(indexes):
        for fr in _jit_roots(indexes, indexes[path]):
            if id(fr.node) not in queued:
                queued.add(id(fr.node))
                pending.append(fr)

    n_roots = 0
    n_captures = 0
    vocab_cache: Dict[tuple, Tuple[Set[str], _Vocab]] = {}
    while pending:
        fr = pending.pop(0)
        idx = indexes[fr.module.path]
        path = fr.module.path
        if not fr.parents:
            continue            # module-level jit fn: captures are
                                # module bindings — nothing cacheable
                                # outlives the function object
        ck = (path, fr.classname)
        cached = vocab_cache.get(ck)
        if cached is None:
            relatives = _related_classes(indexes, fr.classname)
            vocab = _build_vocab(indexes, idx, relatives)
            vocab_cache[ck] = (relatives, vocab)
        else:
            relatives, vocab = cached
        if not vocab.sites:
            # no compile cache in scope: the closure dies with its
            # factory call — jax keys its own cache by function
            # identity, so captures cannot go stale
            continue
        observed = set()
        for suffix, names in observed_all.items():
            if path.endswith(suffix):
                observed |= names
        n_roots += 1
        res = _Resolver(indexes, relatives, vocab, observed)
        ctx = _Ctx(list(fr.parents), idx, skip=fr.node)
        for item, line in _captures_of(fr, idx):
            n_captures += 1
            got = res.resolve(ctx, item)
            if got == "ok":
                continue
            decl = _decl_for(idx, fr, item)
            if decl is not None and decl.justification:
                # an UNjustified declaration does not silence — it is
                # itself a finding (the molint suppression discipline)
                decl.used = True
                continue
            if got == "weak":
                findings.append(Finding(
                    "weak-key", path, line,
                    f"traced closure {fr.name!r} captures {item!r} "
                    f"whose only path into the compile key is "
                    f"len()/id() — key the CONTENT (the PR-7 "
                    f"stale-LUT class) or declare "
                    f"`# mokey: invariant={item.split('.')[-1]} "
                    f"-- why`"))
            else:
                findings.append(Finding(
                    "key-capture", path, line,
                    f"traced closure {fr.name!r} captures {item!r} "
                    f"— not a traced argument, not resolvable to "
                    f"the enclosing compile key, not runtime-"
                    f"audited, and not declared "
                    f"`# mokey: invariant={item.split('.')[-1]} "
                    f"-- why` (the stale-compiled-program class)"))
        # shared step bodies produced by factories a capture chased
        # become roots too (`chain = self._make_chain_fn(...)`)
        for fac in res.derived_factories:
            for sub in _nested_defs(indexes, fac):
                if id(sub.node) not in queued:
                    queued.add(id(sub.node))
                    pending.append(sub)

    # declaration meta-rules (the molint suppression discipline)
    for path, idx in sorted(indexes.items()):
        for d in idx.decls:
            if not d.justification:
                findings.append(Finding(
                    "invariant-decl", path, d.lineno,
                    "invariant declaration has no justification text "
                    "(write `# mokey: invariant=<name> -- why`)"))

    findings.sort(key=Finding.sort_key)
    stats = {"files": len(project.modules),
             "roots": n_roots,
             "captures": n_captures,
             "findings": len(findings),
             "seconds": round(time.perf_counter() - t0, 3)}
    if record:
        LAST_RUN = dict(stats)
        LAST_RUN["ts"] = time.time()
        LAST_RUN["findings_list"] = [f.format() for f in findings[:50]]
    return findings, stats


#: last completed run, for mo_ctl('keys','status') introspection
LAST_RUN: Optional[dict] = None


def last_run_status() -> dict:
    st: dict = {"observed_sites": sorted(load_observed())}
    if LAST_RUN is None:
        st["last_run"] = None
    else:
        st["last_run"] = {k: LAST_RUN[k]
                          for k in ("files", "roots", "captures",
                                    "findings", "ts")}
        st["last_run"]["findings_list"] = LAST_RUN["findings_list"]
    return st


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m tools.mokey",
        description="trace-capture / cache-key completeness analyzer "
                    "(see README 'Static analysis').")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: matrixone_tpu/)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--root", default=None)
    ap.add_argument("--observed", default=None,
                    help="handshake export path (default: "
                         "tools/mokey/observed_captures.json)")
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root or repo_root())
    src = [os.path.abspath(p) for p in args.paths] or None
    findings, stats = run_checks(root, src_paths=src,
                                 observed_path=args.observed)
    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
    print(f"mokey: {stats['roots']} traced closures, "
          f"{stats['captures']} captures, {stats['findings']} "
          f"finding(s) across {stats['files']} file(s) "
          f"[{stats['seconds']}s]", file=sys.stderr)
    return 1 if findings else 0

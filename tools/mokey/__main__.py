import sys

from tools.mokey import main

if __name__ == "__main__":
    sys.exit(main())

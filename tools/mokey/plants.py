"""mokey planted-bug smoke drills — the precheck `--key-smoke` stage.

Proves the analyzer catches what it claims to, on BOTH sides, in a few
seconds (mirrors tools/mosan.plant_eviction_race and tools/moqa's
plant drills):

  static   — copy the planted fixture pairs (tests/mokey_fixtures/)
             into a temp tree and run the static pass: the PR-7
             length-only-key plant must report `weak-key`, the PR-13
             dropped-arity plant `key-capture`, and both clean twins
             must stay quiet;
  runtime  — execute the same planted caches with the auditor armed:
             same-cardinality dictionary churn / a grown lifted tuple
             collide on the planted keys and must surface as
             `key-capture-mismatch` findings carrying both stacks,
             while the clean twins re-key and stay quiet.
"""

from __future__ import annotations

import importlib.util
import os
import shutil
import tempfile

from tools.molint import repo_root


def fixture_dir() -> str:
    return os.path.join(repo_root(), "tests", "mokey_fixtures")


_PAIRS = (
    ("stale_dict_bad.py", "stale_dict_good.py", "weak-key"),
    ("lit_arity_bad.py", "lit_arity_good.py", "key-capture"),
)


def run_static_smoke() -> dict:
    """Static pass over a planted temp tree: both plants caught with
    the expected rule, both clean twins quiet."""
    from tools import mokey
    out = {"caught": {}, "clean": {}, "ok": True}
    with tempfile.TemporaryDirectory(prefix="mokey_smoke_") as tmp:
        for fn in [f for pair in _PAIRS for f in pair[:2]]:
            shutil.copy(os.path.join(fixture_dir(), fn),
                        os.path.join(tmp, fn))
        for bad, good, rule in _PAIRS:
            fb, _ = mokey.run_checks(tmp,
                                     src_paths=[os.path.join(tmp, bad)],
                                     record=False)
            fg, _ = mokey.run_checks(tmp,
                                     src_paths=[os.path.join(tmp,
                                                             good)],
                                     record=False)
            out["caught"][bad] = any(f.rule == rule for f in fb)
            out["clean"][good] = not fg
            out["ok"] = out["ok"] and out["caught"][bad] \
                and out["clean"][good]
    return out


def _load_fixture(fn: str):
    path = os.path.join(fixture_dir(), fn)
    spec = importlib.util.spec_from_file_location(
        f"mokey_fixture_{fn[:-3]}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_runtime_smoke() -> dict:
    """One audit round-trip per plant: drive the planted caches under
    the armed auditor, assert the collision is reported (with both
    stacks) and the clean twins stay quiet."""
    import numpy as np

    from matrixone_tpu.utils import keys
    out = {"caught": {}, "clean": {}, "ok": True}
    with keys.armed_scope(), keys.capture() as cap:
        bad = _load_fixture("stale_dict_bad.py").LutProgramCache(
            ["aa", "bb"])
        codes = np.asarray([0, 1, 0], np.int32)
        bad.run(codes)
        bad.rotate(["zq", "bb"])       # same cardinality, new content
        bad.run(codes)
        got = cap.findings()
        out["caught"]["stale_dict_bad.py"] = any(
            f.name == "lut_content" and f.record_stack and f.hit_stack
            for f in got)
    with keys.armed_scope(), keys.capture() as cap:
        good = _load_fixture("stale_dict_good.py").LutProgramCache(
            ["aa", "bb"])
        good.run(codes)
        good.rotate(["zq", "bb"])
        good.run(codes)
        out["clean"]["stale_dict_good.py"] = not cap.findings()
    with keys.armed_scope(), keys.capture() as cap:
        bad = _load_fixture("lit_arity_bad.py").LiftedProgramCache()
        xs = np.asarray([1.0, 2.0])
        bad.run(xs, "f8x2", (2.0,))
        bad.run(xs, "f8x2", (2.0, 3.0))   # arity grew, key did not
        got = cap.findings()
        out["caught"]["lit_arity_bad.py"] = any(
            f.name in ("lift_arity", "baked_values") for f in got)
    with keys.armed_scope(), keys.capture() as cap:
        good = _load_fixture("lit_arity_good.py").LiftedProgramCache()
        good.run(xs, "f8x2", (2.0,))
        good.run(xs, "f8x2", (2.0, 3.0))
        out["clean"]["lit_arity_good.py"] = not cap.findings()
    out["ok"] = all(out["caught"].values()) and all(
        out["clean"].values())
    return out

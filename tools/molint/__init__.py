"""molint — AST-driven invariant checkers for the cross-cutting
conventions this codebase is built on.

The correctness of the engine rests on rules no type system sees:
"never block under the commit lock", "every RPC carries the caller's
deadline", "a catalog write bumps ddl_gen in the same function",
"jitted bodies are trace-pure", "every mo_* metric is registered
exactly once", "every fault site has a chaos drill".  The reference
system holds its 1.94M lines together with `go vet`, the race detector
and bespoke linters; this is the Python analogue: a shared file walker,
one checker per invariant, and a tier-1 gate (tests/test_molint.py)
that fails the build when a new subsystem re-breaks an old rule.

Findings print as `path:lineno rule message`.  A finding is silenced by
a suppression comment on the offending line (or a standalone comment on
the line directly above):

    # molint: disable=<rule>[,<rule>] -- <justification, required>

The justification text is mandatory — an unexplained suppression is
itself a finding (rule `suppression`).  `# molint: disable-file=<rule>
-- why` anywhere in a file's first 20 lines suppresses the rule for the
whole file.  The broad-except checker additionally honours the legacy
`# noqa` convention inherited from tools/lint_excepts.py.

Programmatic surface (used by mo_ctl('lint', ...) and the tests):

    findings, stats = molint.run_checks(root)        # scan <root>/matrixone_tpu
    molint.last_run_status()                         # ops introspection
"""

from __future__ import annotations

import ast
import os
import re
import sys
import time
from typing import Dict, Iterable, List, Optional, Tuple

#: directories never scanned (as path components)
SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules",
             "molint_fixtures", "mokey_fixtures"}

_SUPPRESS_RE = re.compile(
    r"#\s*molint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,-]+)\s*(?P<rest>.*)$")
#: the justification follows the rule list after any dash/em-dash/colon
_JUST_STRIP = re.compile(r"^[\s:;—-]+")


class Finding:
    """One invariant violation at a source location."""

    __slots__ = ("rule", "path", "lineno", "message")

    def __init__(self, rule: str, path: str, lineno: int, message: str):
        self.rule = rule
        self.path = path
        self.lineno = int(lineno)
        self.message = message

    def format(self) -> str:
        return f"{self.path}:{self.lineno} {self.rule} {self.message}"

    def sort_key(self):
        return (self.path, self.lineno, self.rule)

    def as_dict(self) -> dict:
        return {"path": self.path, "lineno": self.lineno,
                "rule": self.rule, "message": self.message}

    def __repr__(self):
        return f"<Finding {self.format()}>"


class Suppression:
    __slots__ = ("lineno", "rules", "justification", "target_line",
                 "file_level", "wants_file_level", "used")

    def __init__(self, lineno: int, rules: List[str], justification: str,
                 target_line: int, file_level: bool,
                 wants_file_level: bool = False):
        self.lineno = lineno
        self.rules = rules
        self.justification = justification
        #: the code line this suppression covers: its own line, or (for
        #: a standalone comment, possibly wrapped over several comment
        #: lines) the next non-comment line below it
        self.target_line = target_line
        self.file_level = file_level
        #: a disable-file= comment past the line-20 window: inert as
        #: file-level — surfaced by the meta-rule instead of silently
        #: downgrading to a one-line suppression
        self.wants_file_level = wants_file_level
        self.used = False

    def covers(self, rule: str, lineno: int) -> bool:
        if rule not in self.rules and "all" not in self.rules:
            return False
        if self.file_level:
            return True
        return lineno in (self.lineno, self.target_line)


class PyModule:
    """One parsed source file: path (repo-relative when possible), text,
    lines, AST, suppressions.  `tree` is None when the file does not
    parse — the runner reports that as a `parse` finding."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath
        try:
            with open(abspath, encoding="utf-8") as f:
                self.text = f.read()
        except (OSError, UnicodeDecodeError) as e:
            # unreadable/mis-encoded file: a `parse` finding, not a
            # crashed gate
            self.text = ""
            self.lines = []
            self.tree: Optional[ast.AST] = None
            self.parse_error: Optional[str] = str(e)
            self.modname = ""
            self.suppressions: List[Suppression] = []
            return
        self.lines = self.text.splitlines()
        try:
            self.tree = ast.parse(self.text)
            self.parse_error = None
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        #: dotted module name guess (for import resolution)
        mod = relpath[:-3] if relpath.endswith(".py") else relpath
        mod = mod.replace(os.sep, ".").replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        self.modname = mod
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> List[Suppression]:
        out = []
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = [r.strip() for r in m.group("rules").split(",")
                     if r.strip()]
            just = _JUST_STRIP.sub("", m.group("rest")).strip()
            standalone = line[: m.start()].strip() == ""
            target = i
            if standalone:
                # a wrapped justification continues on comment lines;
                # the suppression covers the first code line below
                j = i
                while j < len(self.lines) and (
                        not self.lines[j].strip()
                        or self.lines[j].strip().startswith("#")):
                    j += 1
                target = j + 1
            wants_file = bool(m.group("file"))
            file_level = wants_file and i <= 20
            out.append(Suppression(i, rules, just, target, file_level,
                                   wants_file_level=wants_file))
        return out


#: process-global parse cache: abspath -> (mtime_ns, size, PyModule).
#: ONE parse per file per process, shared by every run_checks caller —
#: the tier-1 gate, the per-rule fixture invocations, precheck's
#: concurrent legs and tools/mokey all construct Projects over the
#: same tree, and re-parsing the ~130-file package per construction
#: was the suite's O(invocations × files) hot spot.  Checker memo
#: attributes (_molint_aliases, _attr_locals) ride the cached module,
#: which is exactly the sharing the checkers already assume.
_PARSE_CACHE: Dict[str, tuple] = {}
_PARSE_LOCK = __import__("threading").Lock()


def _load_module(abspath: str, relpath: str) -> PyModule:
    try:
        st = os.stat(abspath)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        return PyModule(abspath, relpath)   # unreadable: parse finding
    with _PARSE_LOCK:
        hit = _PARSE_CACHE.get(abspath)
        if hit is not None and hit[0] == sig and hit[1].path == relpath:
            return hit[1]
    mod = PyModule(abspath, relpath)
    with _PARSE_LOCK:
        _PARSE_CACHE[abspath] = (sig, mod)
    return mod


class Project:
    """Everything the checkers see: parsed source modules plus (for the
    coverage-style checkers) parsed test modules.  `complete` says the
    scan covers the whole default package — corpus-global sub-rules
    (armed-spec resolution, dead metrics) only make sense then, and
    skip themselves on partial scans of a few files."""

    def __init__(self, root: str, src_paths: List[str],
                 tests_dir: Optional[str] = None,
                 complete: bool = True):
        self.root = os.path.abspath(root)
        self.complete = complete
        self.modules: List[PyModule] = []
        self.test_modules: List[PyModule] = []
        for p in src_paths:
            self.modules.extend(self._load_tree(p))
        if tests_dir and os.path.isdir(tests_dir):
            self.test_modules = self._load_tree(tests_dir)

    def _load_tree(self, path: str) -> List[PyModule]:
        path = os.path.abspath(path)
        mods: List[PyModule] = []
        if os.path.isfile(path):
            mods.append(_load_module(path, self._rel(path)))
            return mods
        for dirpath, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for fn in sorted(files):
                if fn.endswith(".py"):
                    ap = os.path.join(dirpath, fn)
                    mods.append(_load_module(ap, self._rel(ap)))
        return mods

    def _rel(self, abspath: str) -> str:
        rel = os.path.relpath(abspath, self.root)
        return abspath if rel.startswith("..") else rel

    def module_by_suffix(self, suffix: str) -> Optional[PyModule]:
        for m in self.modules:
            if m.path.endswith(suffix):
                return m
        return None


class Checker:
    """Base class.  Subclasses set `rule` + `description` and implement
    check(project, config) -> iterable of Finding.  `config` is the
    rule's entry from the merged config dict (overridable per run — the
    fixture tests point registry/tests paths at snippets)."""

    rule = "?"
    description = "?"
    default_config: dict = {}

    def check(self, project: Project,
              config: dict) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


def all_checkers() -> List[Checker]:
    from tools.molint import checkers
    return [cls() for cls in checkers.ALL]


def rule_table() -> List[Tuple[str, str]]:
    return [(c.rule, c.description) for c in all_checkers()]


def _apply_suppressions(project: Project, findings: List[Finding]):
    """Drop findings covered by a valid suppression; emit `suppression`
    findings for disable comments with no justification.  Returns
    (kept_findings, suppressed_count)."""
    by_path: Dict[str, PyModule] = {m.path: m for m in
                                    project.modules + project.test_modules}
    known = {c.rule for c in all_checkers()} | {"all", "parse"}
    kept: List[Finding] = []
    suppressed = 0
    for f in findings:
        mod = by_path.get(f.path)
        sup = None
        if mod is not None:
            for s in mod.suppressions:
                if s.justification and s.covers(f.rule, f.lineno):
                    sup = s
                    break
        if sup is not None:
            sup.used = True
            suppressed += 1
        else:
            kept.append(f)
    # meta-rule: every disable comment must carry a justification and
    # name real rules (an unexplained or misspelled suppression silently
    # rots — the next reader cannot tell intent from typo).  Test files
    # are covered too: their suppressions are honored above, so their
    # malformations must be reported symmetrically
    for mod in project.modules + project.test_modules:
        for s in mod.suppressions:
            if not s.justification:
                kept.append(Finding(
                    "suppression", mod.path, s.lineno,
                    "suppression comment has no justification text "
                    "(write `# molint: disable=<rule> -- why`)"))
            if s.wants_file_level and not s.file_level:
                kept.append(Finding(
                    "suppression", mod.path, s.lineno,
                    "disable-file= only works within a file's first "
                    "20 lines — this one is inert as file-level "
                    "(it covers only its own line)"))
            for r in s.rules:
                if r not in known:
                    kept.append(Finding(
                        "suppression", mod.path, s.lineno,
                        f"unknown rule {r!r} in suppression comment"))
    return kept, suppressed


#: last completed run, for mo_ctl('lint','status') introspection
LAST_RUN: Optional[dict] = None


def run_checks(root: str, src_paths: Optional[List[str]] = None,
               tests_dir: Optional[str] = None,
               rules: Optional[List[str]] = None,
               config: Optional[Dict[str, dict]] = None,
               record: bool = True):
    """Run the suite.  `root` anchors relative finding paths; scan
    defaults to <root>/matrixone_tpu with <root>/tests as the test
    corpus.  Returns (findings, stats)."""
    global LAST_RUN
    root = os.path.abspath(root)
    default_pkg = os.path.join(root, "matrixone_tpu")
    if src_paths is None:
        src_paths = [default_pkg]
    if tests_dir is None:
        cand = os.path.join(root, "tests")
        tests_dir = cand if os.path.isdir(cand) else None
    # scanning the whole default package (implicitly or by naming it)
    # gives the corpus-global sub-rules their full context; a partial
    # file/dir scan does not, and they skip themselves (checkers read
    # project.complete) instead of mass-reporting false gaps
    complete = [os.path.normpath(os.path.abspath(p))
                for p in src_paths] == [os.path.normpath(default_pkg)]
    project = Project(root, src_paths, tests_dir, complete=complete)
    checkers = all_checkers()
    if rules:
        want = set(rules)
        unknown = want - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.rule in want]
    findings: List[Finding] = []
    # test modules included: an unparseable test file silently drops
    # its armed fault specs, and fault-coverage would then blame
    # healthy source sites as "never armed"
    for mod in project.modules + project.test_modules:
        if mod.tree is None:
            findings.append(Finding("parse", mod.path, 1,
                                    f"file does not parse: "
                                    f"{mod.parse_error}"))
    timings: Dict[str, float] = {}
    for c in checkers:
        cfg = dict(c.default_config)
        cfg.update((config or {}).get(c.rule, {}))
        t0 = time.perf_counter()
        findings.extend(c.check(project, cfg))
        timings[c.rule] = round(time.perf_counter() - t0, 4)
    findings, suppressed = _apply_suppressions(project, findings)
    if rules:
        findings = [f for f in findings
                    if f.rule in set(rules) | {"parse", "suppression"}]
    findings.sort(key=Finding.sort_key)
    stats = {"checkers": len(checkers),
             "files": len(project.modules),
             "findings": len(findings),
             "suppressions_used": suppressed,
             "rules": sorted(c.rule for c in checkers),
             #: per-checker wall seconds, slowest first — the growing
             #: suite's next hot spot must stay visible (mo_ctl
             #: ('lint','status') and the CLI summary both surface it)
             "checker_seconds": dict(sorted(
                 timings.items(), key=lambda kv: -kv[1]))}
    if record:
        LAST_RUN = dict(stats)
        LAST_RUN["ts"] = time.time()
        LAST_RUN["findings_list"] = [f.format() for f in findings[:50]]
    return findings, stats


def last_run_status() -> dict:
    """mo_ctl('lint','status') payload: suite shape + last-run summary."""
    st = {"checkers": len(all_checkers()),
          "rules": sorted(c.rule for c in all_checkers())}
    if LAST_RUN is None:
        st["last_run"] = None
    else:
        st["last_run"] = {k: LAST_RUN[k]
                          for k in ("findings", "files",
                                    "suppressions_used",
                                    "checker_seconds", "ts")}
        st["last_run"]["findings_list"] = LAST_RUN["findings_list"]
    return st


def repo_root() -> str:
    """The repo this tools/ package sits in."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog="python -m tools.molint",
        description="AST-driven invariant checkers (see README "
                    "'Static analysis').")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: matrixone_tpu/)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable, or comma-"
                         "separated)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from tools/)")
    ap.add_argument("--tests", default=None,
                    help="test corpus dir for the coverage checkers "
                         "(default: <root>/tests)")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule, desc in rule_table():
            print(f"{rule:22s} {desc}")
        return 0
    root = os.path.abspath(args.root or repo_root())
    src = [os.path.abspath(p) for p in args.paths] or None
    rules = None
    if args.rule:
        rules = [r for part in args.rule for r in part.split(",") if r]
    try:
        findings, stats = run_checks(root, src_paths=src,
                                     tests_dir=args.tests, rules=rules)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
    secs = stats.get("checker_seconds", {})
    slowest = ", ".join(f"{r}={s}s" for r, s in list(secs.items())[:3])
    print(f"checker wall time (slowest first): {slowest}"
          + (f" (+{len(secs) - 3} more)" if len(secs) > 3 else ""),
          file=sys.stderr)
    if findings:
        print(f"{len(findings)} finding(s) across {stats['files']} "
              f"file(s); {stats['suppressions_used']} suppressed",
              file=sys.stderr)
        return 1
    return 0

"""`python -m tools.molint [paths...] [--rule X] [--json]` — run the
invariant checker suite standalone (CI wires it through
`python -m tools.precheck`)."""

import sys

from tools.molint import main

if __name__ == "__main__":
    sys.exit(main())

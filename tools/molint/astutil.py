"""Small shared AST helpers for the molint checkers."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def aliases_of(mod) -> Dict[str, str]:
    """Cached import_aliases for a PyModule (walking the whole tree per
    function turns the suite O(n^2) — the 12s hot spot the first
    profile found)."""
    cached = getattr(mod, "_molint_aliases", None)
    if cached is None:
        cached = import_aliases(mod.tree) if mod.tree is not None else {}
        mod._molint_aliases = cached
    return cached


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> dotted module/symbol it refers to, from every
    import statement in the file (module-level and nested)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


class FuncInfo:
    __slots__ = ("node", "name", "qualname", "classname", "module")

    def __init__(self, node, name, qualname, classname, module):
        self.node = node
        self.name = name
        self.qualname = qualname
        self.classname = classname
        self.module = module            # PyModule


def iter_functions(mod) -> Iterator[FuncInfo]:
    """Every function/method in a module with its enclosing class (one
    level — nested defs inherit the outer function's class)."""
    if mod.tree is None:
        return

    def walk(node, classname: Optional[str], prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield FuncInfo(child, child.name, qn, classname, mod)
                yield from walk(child, classname, qn + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child.name, child.name + ".")
            else:
                yield from walk(child, classname, prefix)

    yield from walk(mod.tree, None, "")


def walk_skip_nested_funcs(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function
    definitions (their bodies run at another time)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def str_literals(tree: ast.AST) -> Iterator[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


def first_arg_str(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None

"""Checker registry.  Each module contributes one rule; ALL is the
ordered suite (`python -m tools.molint --list-rules`)."""

from tools.molint.checkers.jit_purity import JitPurityChecker
from tools.molint.checkers.lock_discipline import LockDisciplineChecker
from tools.molint.checkers.deadline import DeadlineChecker
from tools.molint.checkers.cache_invalidation import \
    CacheInvalidationChecker
from tools.molint.checkers.metric_hygiene import MetricHygieneChecker
from tools.molint.checkers.fault_coverage import FaultCoverageChecker
from tools.molint.checkers.broad_except import BroadExceptChecker
from tools.molint.checkers.san_adoption import SanAdoptionChecker
from tools.molint.checkers.knob_doc import KnobDocChecker
from tools.molint.checkers.span_hygiene import SpanHygieneChecker

ALL = [
    JitPurityChecker,
    LockDisciplineChecker,
    DeadlineChecker,
    CacheInvalidationChecker,
    MetricHygieneChecker,
    FaultCoverageChecker,
    BroadExceptChecker,
    SanAdoptionChecker,
    KnobDocChecker,
    SpanHygieneChecker,
]

"""broad-except: no unjustified `except Exception` / `except
BaseException` / bare `except:` anywhere in the package.

A broad catch in the RPC or wire layers is how partial failures turn
into silent data loss; in the engine it is how a constraint error
becomes wrong rows.  Every broad handler must either narrow its type or
carry a justification — either the molint suppression syntax or the
legacy `# noqa: BLE001 — why` convention from tools/lint_excepts.py
(which is now a thin shim over this checker).

The noqa may sit on the `except` line itself or be the sole content of
the line directly above (the layout long lines use).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tools.molint import Checker, Finding, Project

_NOQA = re.compile(r"#\s*noqa")

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


class BroadExceptChecker(Checker):
    rule = "broad-except"
    description = ("`except Exception`/`except:` must narrow its type "
                   "or carry a justification comment")
    default_config = {
        #: restrict to these path prefixes; None = every scanned file
        "dirs": None,
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        dirs = config.get("dirs")
        for mod in project.modules:
            if mod.tree is None:
                continue
            if dirs is not None and not any(
                    mod.path.startswith(d) for d in dirs):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node):
                    continue
                line = mod.lines[node.lineno - 1] \
                    if node.lineno <= len(mod.lines) else ""
                prev = mod.lines[node.lineno - 2] if node.lineno >= 2 \
                    else ""
                if _NOQA.search(line) or (
                        prev.strip().startswith("#")
                        and _NOQA.search(prev)):
                    continue
                yield Finding(
                    self.rule, mod.path, node.lineno,
                    "unjustified broad except (narrow the type or add "
                    "'# noqa: BLE001 -- why' / "
                    "'# molint: disable=broad-except -- why'): "
                    + line.strip())

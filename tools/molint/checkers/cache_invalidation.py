"""cache-invalidation: catalog-shape mutations bump `ddl_gen` in the
same function; index state stays self-consistent.

The PR-4 serving caches pin every plan and result to the engine's
`ddl_gen`.  That only works if EVERY code path that changes catalog
shape bumps it — the PR-4/5 review rounds each caught a path that
didn't (logtail replay, UDF drop).  Encoded:

  * a function that mutates a catalog container — subscript/del/pop/
    clear/rebind on `.tables`, `.indexes`, `.snapshots`, `.stages`,
    `.publications`, `.dynamic_tables`, or add/discard on `.sources` —
    must also bump `ddl_gen` (`x.ddl_gen += 1`, an assignment to it, or
    a call to a method that bumps, e.g. `register_index`) in the SAME
    function, or carry a suppression saying why the shape didn't
    change.  `__init__` constructors are exempt (there is no cache to
    invalidate before the engine exists).
  * a function that replaces `IndexMeta.index_obj` must also write
    `.dirty` in the same function — the pair is the index's version:
    an `index_obj` swap with a stale dirty flag either re-serves the
    old index or rebuilds forever.
  * a function that mutates materialized-view state — subscript/del/
    pop/clear/rebind on `.groups` of a runtime-shaped receiver
    (`self.` inside a class that references `watermark`, or an
    `rt.`/`state.`/`runtime.` receiver anywhere) — must advance the
    view `watermark` (an assignment, or a call to a state method that
    does: replace_state / merge_delta / invalidate) or bump `ddl_gen`
    in the same branch.  The watermark is the view's version: readers
    and the serving caches pin freshness on it exactly like ddl_gen
    (matrixone_tpu/mview).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import dotted, iter_functions, \
    walk_skip_nested_funcs

_CATALOG_ATTRS = ("tables", "indexes", "snapshots", "stages",
                  "publications", "dynamic_tables")
_SET_ATTRS = ("sources",)
#: receiver names that denote an engine/catalog object when the
#: mutation happens outside the Engine class itself
_ENGINE_RECEIVERS = {"rep", "eng", "engine", "catalog", "replica",
                     "cat"}
#: materialized-view state containers + the receivers that denote a
#: view runtime outside its own class
_VIEWSTATE_ATTRS = ("groups",)
_VIEWSTATE_RECEIVERS = {"rt", "state", "runtime", "view"}


def _viewstate_attr(node: ast.AST, stateish: bool) -> Optional[str]:
    """'groups' when node is an attr chain ending in a view-state
    container on a runtime-shaped receiver (see module docstring)."""
    d = dotted(node)
    if d is None:
        return None
    parts = d.split(".")
    term = parts[-1]
    if term not in _VIEWSTATE_ATTRS or len(parts) < 2:
        return None
    recv = parts[-2]
    if recv == "self":
        return term if stateish else None
    if recv in _VIEWSTATE_RECEIVERS:
        return term
    return None


def _container_attr(node: ast.AST, catalogish: bool) -> Optional[str]:
    """'tables' when node is an attr chain ending in a catalog
    container on an engine-shaped receiver: `self.tables` inside a
    class that knows ddl_gen (catalogish=True), or `rep.tables`/
    `engine.tables`/... anywhere.  A planner helper's `env.tables` or a
    worker's private `self.indexes` is not the catalog."""
    d = dotted(node)
    if d is None:
        return None
    parts = d.split(".")
    term = parts[-1]
    if term not in _CATALOG_ATTRS or len(parts) < 2:
        return None
    recv = parts[-2]
    if recv == "self":
        return term if catalogish else None
    if recv in _ENGINE_RECEIVERS:
        return term
    return None


class CacheInvalidationChecker(Checker):
    rule = "cache-invalidation"
    description = ("catalog container mutations bump ddl_gen in the "
                   "same function; index_obj swaps update .dirty")
    default_config = {
        #: method calls that bump ddl_gen on the callee's behalf
        #: (Engine.create_table/create_external/register_index each
        #: contain the bump; a function routing through them is covered)
        "bumping_calls": ("register_index", "create_table",
                          "create_external"),
        #: view-state methods that advance the watermark on the
        #: callee's behalf (mview/maintain.ViewRuntime)
        "watermark_calls": ("replace_state", "merge_delta",
                            "invalidate"),
        #: function names exempt (constructors build, not mutate)
        "exempt_functions": ("__init__",),
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        bumping = set(config["bumping_calls"])
        wm_calls = set(config["watermark_calls"])
        exempt = set(config["exempt_functions"])
        # classes whose `self.` IS the catalog: any class whose body
        # mentions ddl_gen (Engine and its replica/tenant wrappers);
        # classes whose `self.` IS view state: any class referencing
        # a watermark attribute (ViewRuntime and test doubles)
        catalog_classes = set()
        state_classes = set()
        for mod in project.modules:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Attribute) \
                                and sub.attr == "ddl_gen":
                            catalog_classes.add(node.name)
                        if isinstance(sub, ast.Attribute) \
                                and sub.attr == "watermark":
                            state_classes.add(node.name)
        for mod in project.modules:
            if mod.tree is None:
                continue
            for fi in iter_functions(mod):
                if fi.name in exempt:
                    continue
                yield from self._check_func(
                    fi, bumping, wm_calls,
                    fi.classname in catalog_classes,
                    fi.classname in state_classes)

    def _check_func(self, fi, bumping, wm_calls, catalogish: bool,
                    stateish: bool) -> Iterable[Finding]:
        # Branch-aware: a bump covers a mutation only when it sits in
        # the SAME if/elif/else arm or an enclosing one.  Function-wide
        # satisfaction let one bumping branch of a dispatcher (e.g. a
        # WAL-replay apply()) whitelist every other branch's mutation —
        # the exact shape the replica staleness hole hid in.  Regions
        # are if-arms; loops/with/try are transparent.
        mutations: List[tuple] = []      # (lineno, description, region)
        vs_mutations: List[tuple] = []   # view-state mutation sites
        index_obj_writes: List[int] = []
        dirty_writes = False
        bump_regions: List[tuple] = []
        wm_regions: List[tuple] = []     # watermark advances

        def visit(node, region):
            nonlocal dirty_writes
            if isinstance(node, (ast.AugAssign, ast.Assign)):
                targets = [node.target] \
                    if isinstance(node, ast.AugAssign) else node.targets
                for t in targets:
                    d = dotted(t)
                    if d and d.split(".")[-1] == "ddl_gen":
                        bump_regions.append(region)
                    if d and d.split(".")[-1] == "watermark":
                        wm_regions.append(region)
                    if d and d.split(".")[-1] == "dirty":
                        dirty_writes = True
                    if d and d.split(".")[-1] == "index_obj":
                        index_obj_writes.append(node.lineno)
                    # rebinding a whole container: rep.tables = {}
                    if isinstance(t, ast.Attribute):
                        term = _container_attr(t, catalogish)
                        if term:
                            mutations.append(
                                (node.lineno, f"rebinds .{term}",
                                 region))
                        term = _viewstate_attr(t, stateish)
                        if term:
                            vs_mutations.append(
                                (node.lineno, f"rebinds .{term}",
                                 region))
                    # subscript store: self.tables[name] = t
                    if isinstance(t, ast.Subscript):
                        term = _container_attr(t.value, catalogish)
                        if term:
                            mutations.append(
                                (node.lineno, f"writes .{term}[...]",
                                 region))
                        term = _viewstate_attr(t.value, stateish)
                        if term:
                            vs_mutations.append(
                                (node.lineno, f"writes .{term}[...]",
                                 region))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        term = _container_attr(t.value, catalogish)
                        if term:
                            mutations.append(
                                (node.lineno, f"deletes from .{term}",
                                 region))
                        term = _viewstate_attr(t.value, stateish)
                        if term:
                            vs_mutations.append(
                                (node.lineno, f"deletes from .{term}",
                                 region))
            elif isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                parts = d.split(".")
                term = parts[-1]
                if term in bumping:
                    bump_regions.append(region)
                if term in wm_calls:
                    wm_regions.append(region)
                if term in ("pop", "clear", "popitem", "setdefault",
                            "update") and len(parts) >= 2:
                    cont = _container_attr(node.func.value, catalogish)
                    if cont:
                        mutations.append(
                            (node.lineno, f".{cont}.{term}(...)",
                             region))
                    cont = _viewstate_attr(node.func.value, stateish)
                    if cont:
                        vs_mutations.append(
                            (node.lineno, f".{cont}.{term}(...)",
                             region))
                if term in ("add", "discard", "remove") \
                        and len(parts) >= 2:
                    d2 = dotted(node.func.value) or ""
                    p2 = d2.split(".")
                    if p2[-1] in _SET_ATTRS and len(p2) >= 2 and (
                            (p2[-2] == "self" and catalogish)
                            or p2[-2] in _ENGINE_RECEIVERS):
                        mutations.append(
                            (node.lineno, f".{p2[-1]}.{term}(...)",
                             region))

        def walk(node, region):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.If):
                    visit(child.test, region)
                    walk(child.test, region)
                    for arm, block in ((0, child.body),
                                       (1, child.orelse)):
                        sub = region + ((id(child), arm),)
                        for stmt in block:
                            visit(stmt, sub)
                            walk(stmt, sub)
                    continue
                visit(child, region)
                walk(child, region)

        walk(fi.node, ())

        def covered(region) -> bool:
            return any(region[: len(b)] == b for b in bump_regions)

        def wm_covered(region) -> bool:
            return any(region[: len(b)] == b
                       for b in wm_regions + bump_regions)

        for lineno, what, region in mutations:
            if not covered(region):
                yield Finding(
                    self.rule, fi.module.path, lineno,
                    f"{fi.qualname} {what} but this branch never "
                    f"bumps ddl_gen — cached plans/results outlive "
                    f"the catalog shape")
        for lineno, what, region in vs_mutations:
            if not wm_covered(region):
                yield Finding(
                    self.rule, fi.module.path, lineno,
                    f"{fi.qualname} {what} but this branch never "
                    f"advances the view watermark (or bumps ddl_gen) "
                    f"— view state and its freshness stamp desync")
        if index_obj_writes and not dirty_writes:
            for lineno in index_obj_writes:
                yield Finding(
                    self.rule, fi.module.path, lineno,
                    f"{fi.qualname} replaces IndexMeta.index_obj "
                    f"without updating .dirty — index version and "
                    f"freshness flag desync")

"""deadline-propagation: time budgets follow the call chain; nobody
invents a private timeout or a flat retry sleep.

The PR-2 fabric made deadlines ambient (`deadline_scope` /
`current_deadline`, with the wire carrying `deadline_ms` so servers
re-enter the caller's budget).  The conventions that keep that true:

  * no `sock.settimeout(<numeric constant>)` — a hardcoded socket
    timeout either outlives the caller's budget (the call hangs past
    the deadline) or truncates it.  Derive from
    `current_deadline().remaining()` / a computed budget, or suppress
    with a justification when the value is a poll TICK on a loop that
    `continue`s on timeout (a tick is a wakeup interval, not a
    deadline).  `settimeout(None)` and computed expressions pass.
  * retry loops back off with jitter: a `time.sleep(...)` inside a
    loop that also catches exceptions (the retry shape) must use the
    shared `backoff_delay` helper — a flat sleep synchronizes
    thundering-herd retries across callers.
  * call sites of worker methods that accept a `deadline_ms` parameter
    must pass it (config `must_thread`) — dropping it silently detaches
    the worker call from the statement budget.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import dotted, walk_skip_nested_funcs


def _is_numeric_const(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_numeric_const(node.operand)
    return False


class DeadlineChecker(Checker):
    rule = "deadline-propagation"
    description = ("no hardcoded socket timeouts, jittered backoff in "
                   "retry loops, deadline_ms threaded to worker calls")
    default_config = {
        #: method names whose call sites must pass deadline_ms (keyword
        #: or enough positionals to reach it); (name, min_positional)
        "must_thread": (("udf_eval", 4),),
        #: helper whose presence in a retry loop marks backoff as shared
        "backoff_helper": "backoff_delay",
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        must_thread = dict(config["must_thread"])
        helper = config["backoff_helper"]
        for mod in project.modules:
            if mod.tree is None:
                continue
            # ---- hardcoded settimeout
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "settimeout" and node.args and \
                        _is_numeric_const(node.args[0]):
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        "hardcoded socket timeout "
                        f"settimeout({ast.unparse(node.args[0])}) — "
                        "derive it from current_deadline().remaining() "
                        "(or suppress: poll ticks that continue on "
                        "timeout are not deadlines)")
                # ---- deadline_ms threading at worker seams
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in must_thread:
                    min_pos = must_thread[node.func.attr]
                    kws = {kw.arg for kw in node.keywords}
                    if "deadline_ms" not in kws and \
                            len(node.args) < min_pos:
                        yield Finding(
                            self.rule, mod.path, node.lineno,
                            f".{node.func.attr}(...) call drops "
                            f"deadline_ms — the worker call detaches "
                            f"from the statement budget")
            # ---- flat sleeps in retry loops
            yield from self._retry_sleeps(mod, helper)

    def _retry_sleeps(self, mod, helper: str) -> Iterable[Finding]:
        from tools.molint.astutil import aliases_of
        aliases = aliases_of(mod)
        time_mods = {a for a, target in aliases.items()
                     if target == "time"}
        sleep_names = {a for a, target in aliases.items()
                       if target == "time.sleep"}

        def is_time_sleep(call: ast.Call) -> bool:
            d = dotted(call.func) or ""
            parts = d.split(".")
            if len(parts) == 2 and parts[0] in time_mods \
                    and parts[1] == "sleep":
                return True
            return len(parts) == 1 and parts[0] in sleep_names

        def subtree_has_helper(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if (isinstance(n, ast.Name) and n.id == helper) or \
                        (isinstance(n, ast.Attribute)
                         and n.attr == helper):
                    return True
            return False

        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            body_nodes = list(walk_skip_nested_funcs(loop))
            has_except = any(isinstance(n, ast.ExceptHandler)
                             for n in body_nodes)
            if not has_except:
                continue
            # names bound (anywhere in the loop) to a backoff-derived
            # expression: `delay = min(backoff_delay(a), rem)` makes
            # time.sleep(delay) legitimate
            backoff_names = set()
            for n in body_nodes:
                if isinstance(n, ast.Assign) and \
                        subtree_has_helper(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            backoff_names.add(t.id)
            for n in body_nodes:
                if not (isinstance(n, ast.Call) and is_time_sleep(n)):
                    continue
                # EACH sleep must derive from the helper — one jittered
                # sleep elsewhere in the loop must not excuse a flat one
                args_ok = n.args and (
                    subtree_has_helper(n.args[0])
                    or (isinstance(n.args[0], ast.Name)
                        and n.args[0].id in backoff_names))
                if not args_ok:
                    yield Finding(
                        self.rule, mod.path, n.lineno,
                        "flat time.sleep in a retry loop — derive the "
                        f"delay from the shared {helper}() (jittered "
                        "exponential backoff) so concurrent retries "
                        "don't synchronize")

"""fault-coverage: the chaos surface and the chaos drills stay in sync.

Two directions, both of which have rotted in real systems:

  * a LIVE fault site (`INJECTOR.trigger("name")` in the package) that
    no test ever arms is a degrade path that has never executed — the
    next refactor breaks it silently;
  * an ARMED spec in a test whose name matches no live site is a drill
    that silently stopped drilling (the site was renamed or deleted and
    `trigger()` of an unknown name is a no-op).

Also enforced: trigger names are string literals (coverage analysis is
impossible otherwise) and every live site is listed in the fault
module's docstring — the docstring is the operator-facing catalogue
(`mo_ctl('fault','arm:<spec>')` users read it, not the code).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Tuple

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import dotted, first_arg_str, str_literals

#: 'name:action[...]' literals in tests — the SQL/mo_ctl arming surface
_SPEC_RE = re.compile(
    r"(?:^|arm:|['\"=\s])([a-z][a-z0-9_]*(?:\.[a-z][a-z0-9_]*)+)"
    r":(?:return|sleep|panic|wait)\b")


def _trigger_sites(mod) -> List[Tuple[str, int]]:
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "trigger"):
            continue
        recv = (dotted(fn.value) or "").split(".")[-1]
        if recv != "INJECTOR":
            continue
        name = first_arg_str(node)
        out.append((name if name is not None else "", node.lineno))
    return out


def _armed_names(mod) -> List[Tuple[str, int]]:
    out = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "add" and \
                    (dotted(fn.value) or "").split(".")[-1] == "INJECTOR":
                name = first_arg_str(node)
                if name is None:
                    for kw in node.keywords:
                        if kw.arg == "name" and isinstance(
                                kw.value, ast.Constant):
                            name = kw.value.value
                if name:
                    out.append((name, node.lineno))
    for text, lineno in str_literals(mod.tree):
        for m in _SPEC_RE.finditer(text):
            out.append((m.group(1), lineno))
    return out


class FaultCoverageChecker(Checker):
    rule = "fault-coverage"
    description = ("every fault.trigger site is armed by a chaos test "
                   "and every armed spec resolves to a live site")
    default_config = {
        #: path suffix of the injector module (its own trigger() impl
        #: and docstring catalogue live there)
        "fault_module_suffix": "utils/fault.py",
        #: require live sites to be listed in the fault module docstring
        "require_docstring": True,
        #: None = follow project.complete; the armed-spec->live-site
        #: direction needs the FULL site corpus, so a partial scan of a
        #: few files skips it (fixture tests force True)
        "corpus_complete": None,
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        findings: List[Finding] = []
        fault_suffix = config["fault_module_suffix"]
        sites: Dict[str, Tuple[str, int]] = {}
        for mod in project.modules:
            if mod.tree is None or mod.path.endswith(fault_suffix):
                continue
            for name, lineno in _trigger_sites(mod):
                if not name:
                    findings.append(Finding(
                        self.rule, mod.path, lineno,
                        "fault trigger name must be a string literal "
                        "(coverage analysis needs the site name)"))
                    continue
                sites.setdefault(name, (mod.path, lineno))

        armed: Dict[str, Tuple[str, int]] = {}
        for mod in project.test_modules:
            for name, lineno in _armed_names(mod):
                armed.setdefault(name, (mod.path, lineno))

        for name, (path, lineno) in sorted(sites.items()):
            if name not in armed:
                findings.append(Finding(
                    self.rule, path, lineno,
                    f"fault site {name!r} is never armed by any test — "
                    f"its degrade path has never executed"))
        complete = config.get("corpus_complete")
        if complete is None:
            complete = project.complete
        if complete:
            for name, (path, lineno) in sorted(armed.items()):
                if name not in sites:
                    findings.append(Finding(
                        self.rule, path, lineno,
                        f"test arms fault spec {name!r} but no live "
                        f"INJECTOR.trigger site has that name — the "
                        f"drill is a no-op"))

        if config.get("require_docstring"):
            fmod = project.module_by_suffix(fault_suffix)
            if fmod is not None and fmod.tree is not None:
                doc = ast.get_docstring(fmod.tree) or ""
                for name, (path, lineno) in sorted(sites.items()):
                    if name not in doc:
                        findings.append(Finding(
                            self.rule, path, lineno,
                            f"fault site {name!r} missing from the "
                            f"{fault_suffix} docstring catalogue "
                            f"(operators arm from that list)"))
        return findings

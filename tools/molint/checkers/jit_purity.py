"""jit-purity: functions reachable from `jax.jit` / `shard_map` are
trace-pure.

A jitted body executes its Python exactly once per (shape, dtype)
signature at trace time; everything it does besides building the traced
computation is either silently frozen into the compiled program
(wall-clock reads, stateful RNG draws) or a host sync that stalls the
device pipeline (`.item()`, `device_get`, `block_until_ready`).  The
UDF tier enforces this dynamically through its sandbox; engine kernels
get it enforced here, statically.

Roots: functions decorated with `jax.jit` / `partial(jax.jit, ...)` /
`shard_map` (or wrapped via `x = jax.jit(f)` / `shard_map(f, ...)`),
plus everything transitively reachable from them through same-module
calls, `self.` method calls, and one level of project-module attribute
calls (`kmeans.assign(...)`).

Impure operations flagged in reachable functions:

  * `time.*` calls — wall-clock frozen at trace time;
  * stdlib `random.*` and `np.random.*` — stateful RNG draws trace to
    constants (`jax.random` with explicit keys is the pure path and is
    allowed);
  * `.item()`, `float(x)`/`int(x)`/`bool(x)` on non-literals,
    `np.asarray` of a traced value is not detectable — but
    `jax.device_get` / `.block_until_ready()` are and force host sync;
  * `global` declarations and subscript-stores into module-level
    objects — mutating module state from a traced body runs once, at
    trace time, then never again.

A helper shared by a host path and a jitted path that needs host-only
impurity behind a flag should be split, or suppressed with a
justification explaining why the impure branch cannot trace.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import (aliases_of, dotted, iter_functions,
                                  walk_skip_nested_funcs)

_JIT_NAMES = {"jit", "shard_map", "pmap"}


def _is_jit_ref(node: ast.AST) -> bool:
    """Does this expression refer to jax.jit/shard_map/pmap?"""
    d = dotted(node)
    if d is None:
        return False
    return d.split(".")[-1] in _JIT_NAMES


def _jit_wrap_target(call: ast.Call) -> Optional[str]:
    """'f' when call is jit(f, ...) / partial(jit, ...)(f)? — the
    direct `jit(f)` / `shard_map(f, ...)` shape, f a plain Name OR an
    attribute reference (`jax.jit(self._traced_step)` — how fused
    fragments and other class-held trace roots wrap their callables:
    the terminal attribute name resolves against the function index,
    which keeps every same-named definition)."""
    if not (_is_jit_ref(call.func) and call.args):
        return None
    tgt = call.args[0]
    if isinstance(tgt, ast.Name):
        return tgt.id
    if isinstance(tgt, ast.Attribute):
        return tgt.attr
    return None


def _decorated_as_jit(fn) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return True
        if isinstance(dec, ast.Call):
            # @partial(jax.jit, ...) / @jax.jit(...) /
            # @partial(shard_map, mesh=...)
            if _is_jit_ref(dec.func):
                return True
            f = dec.func
            if isinstance(f, (ast.Name, ast.Attribute)) and \
                    (dotted(f) or "").split(".")[-1] == "partial" and \
                    dec.args and _is_jit_ref(dec.args[0]):
                return True
    return False


class JitPurityChecker(Checker):
    rule = "jit-purity"
    description = ("functions reachable from jax.jit/shard_map do not "
                   "read clocks/stateful RNG, sync the host, or mutate "
                   "module globals")
    default_config = {
        #: extra impure dotted-call denylist (terminal match)
        "host_sync_attrs": ("item", "block_until_ready", "device_get",
                            "tolist"),
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        # ---- index every function and module-level name.  The index
        # maps (module, bare name) -> EVERY function with that name
        # (methods included): bare-name call resolution cannot tell
        # same-named definitions apart, and keeping only the first
        # would let a method silently shadow the helper a kernel
        # actually calls.  Over-approximating scans all of them.
        funcs: Dict[Tuple[str, str], List["FuncEntry"]] = {}
        mod_globals: Dict[str, Set[str]] = {}
        roots: Set[Tuple[str, str]] = set()
        mod_alias: Dict[str, Dict[str, Set[str]]] = {}
        mod_factory: Dict[str, Dict[str, Set[str]]] = {}
        jit_targets: List[Tuple[str, str]] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            g = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            g.add(t.id)
                elif isinstance(node, ast.AnnAssign) and \
                        isinstance(node.target, ast.Name):
                    g.add(node.target.id)
            mod_globals[mod.modname] = g
            for fi in iter_functions(mod):
                key = (mod.modname, fi.name)
                funcs.setdefault(key, []).append(FuncEntry(fi))
                if _decorated_as_jit(fi.node):
                    roots.add(key)
            # local aliases a jit wrap may resolve through:
            #   fn = _traced_step            (direct alias)
            #   fn = self._make_step(...)    (factory returning the
            #                                 closure it defines)
            # — the fused-fragment idiom: the wrapped Name is a local
            # variable, not a def, so the plain def lookup misses it
            alias: Dict[str, Set[str]] = {}
            factory: Dict[str, Set[str]] = {}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    v = node.value
                    if isinstance(v, (ast.Name, ast.Attribute)):
                        d = dotted(v)
                        if d:
                            alias.setdefault(t.id, set()).add(
                                d.split(".")[-1])
                    elif isinstance(v, ast.Call):
                        d = dotted(v.func)
                        if d:
                            factory.setdefault(t.id, set()).add(
                                d.split(".")[-1])
            mod_alias[mod.modname] = alias
            mod_factory[mod.modname] = factory
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    tgt = _jit_wrap_target(node)
                    if tgt:
                        jit_targets.append((mod.modname, tgt))

        # ---- resolve every jit wrap target: the named def, plus the
        # transitive local-alias closure (`_step = fn; fn =
        # self._make_step(...)`), plus factory-returned closures.  A
        # factory is matched by BARE NAME ACROSS MODULES: `self._make_
        # step()` at a base-class jit site dispatches virtually to any
        # subclass override, whose module the AST cannot know — rooting
        # every same-named factory's nested defs is the same over-
        # approximation policy as bare-name call resolution
        facs_by_name: Dict[str, List[Tuple[str, "FuncEntry"]]] = {}
        for (m2, nm2), entries in funcs.items():
            for entry in entries:
                facs_by_name.setdefault(nm2, []).append((m2, entry))
        for modname, tgt in jit_targets:
            alias = mod_alias.get(modname, {})
            factory = mod_factory.get(modname, {})
            names = {tgt}
            while True:
                more = {a for n in names for a in alias.get(n, ())} \
                    - names
                if not more:
                    break
                names |= more
            for n in names:
                if (modname, n) in funcs:
                    roots.add((modname, n))
            for fac in {f for n in names for f in factory.get(n, ())}:
                for m2, entry in facs_by_name.get(fac, ()):
                    # the factory's nested defs ARE the traced
                    # bodies it returns; root them all
                    for sub in ast.walk(entry.fi.node):
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)) \
                                and sub is not entry.fi.node \
                                and (m2, sub.name) in funcs:
                            roots.add((m2, sub.name))

        # ---- reachability closure over the call graph
        reach: Set[Tuple[str, str]] = set()
        stack = list(roots)
        while stack:
            key = stack.pop()
            if key in reach or key not in funcs:
                continue
            reach.add(key)
            for entry in funcs[key]:
                for callee in entry.callees():
                    if callee[0] == "*":
                        # unknown receiver: every module's same-named
                        # def (facs_by_name is the by-name index)
                        for m2, e2 in facs_by_name.get(callee[1], ()):
                            k2 = (m2, callee[1])
                            if k2 not in reach:
                                stack.append(k2)
                    elif callee in funcs and callee not in reach:
                        stack.append(callee)

        # ---- impurity scan of every reachable function
        findings: List[Finding] = []
        for key in sorted(reach):
            for entry in funcs[key]:
                findings.extend(self._impurities(
                    entry, key in roots, mod_globals, config))
        return findings

    def _impurities(self, entry, is_root: bool, mod_globals,
                    config) -> Iterable[Finding]:
        fi = entry.fi
        mod = fi.module
        aliases = entry.aliases
        host_sync = set(config["host_sync_attrs"])

        def root_module(d: str) -> str:
            head = d.split(".")[0]
            return aliases.get(head, head)

        for node in walk_skip_nested_funcs(fi.node):
            if isinstance(node, ast.Global):
                yield Finding(
                    self.rule, mod.path, node.lineno,
                    f"{fi.qualname} (reachable from jit) declares "
                    f"`global {', '.join(node.names)}` — module state "
                    f"mutates at trace time only")
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is None:
                    continue
                parts = d.split(".")
                rm = root_module(d)
                if rm == "time" and len(parts) >= 2:
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"{fi.qualname} (reachable from jit) calls "
                        f"{d}() — wall clock freezes at trace time")
                elif (rm == "random" and len(parts) >= 2) or \
                        (len(parts) >= 3 and parts[-2] == "random"
                         and root_module(d) in ("numpy", "np")):
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"{fi.qualname} (reachable from jit) calls "
                        f"stateful RNG {d}() — draws freeze at trace "
                        f"time; use jax.random with an explicit key")
                elif is_root and len(parts) == 1 and \
                        parts[0] in ("float", "int", "bool") and \
                        node.args and not isinstance(node.args[0],
                                                     ast.Constant):
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"{fi.qualname} (jitted) calls {parts[0]}() on "
                        f"a traced value — concretization forces a "
                        f"host sync (ConcretizationTypeError on "
                        f"abstract tracers)")
                elif parts[-1] in host_sync and len(parts) >= 2:
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"{fi.qualname} (reachable from jit) calls "
                        f".{parts[-1]}() — host sync stalls the device "
                        f"pipeline (and fails on tracers)")
            # subscript-store into a module-level object
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in mod_globals.get(mod.modname,
                                                          ()):
                        yield Finding(
                            self.rule, mod.path, node.lineno,
                            f"{fi.qualname} (reachable from jit) "
                            f"stores into module-level "
                            f"{t.value.id!r} — runs once at trace "
                            f"time, never per call")


class FuncEntry:
    def __init__(self, fi):
        self.fi = fi
        self.aliases = aliases_of(fi.module)
        self._callees: Optional[List[Tuple[str, str]]] = None

    def callees(self) -> List[Tuple[str, str]]:
        if self._callees is not None:
            return self._callees
        out: List[Tuple[str, str]] = []
        modname = self.fi.module.modname
        # names bound to instance attributes anywhere in the module
        # (`wop = self._window` — often in the ENCLOSING factory of a
        # nested traced def, so collected module-wide): method calls
        # through them dispatch to classes the AST cannot name, so those
        # calls resolve by bare method name (below)
        attr_locals = getattr(self.fi.module, "_attr_locals", None)
        if attr_locals is None:
            attr_locals = set()
            for node in ast.walk(self.fi.module.tree):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Attribute):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            attr_locals.add(t.id)
            self.fi.module._attr_locals = attr_locals
        for node in walk_skip_nested_funcs(self.fi.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) == 1:
                out.append((modname, parts[0]))
            elif parts[0] == "self" and len(parts) == 2:
                out.append((modname, parts[1]))
            elif len(parts) == 2:
                target = self.aliases.get(parts[0])
                if target:
                    out.append((target, parts[1]))
                elif parts[0] in attr_locals:
                    # method call through an instance-attribute local
                    # (`wop = self._window; ... wop.compute_columns()`):
                    # anything invoked from a trace-reachable body is
                    # itself traced, so over-approximate by bare method
                    # name across modules ("*" is expanded in the
                    # reachability closure)
                    out.append(("*", parts[1]))
            # also: functions passed by name as call arguments
            for a in node.args:
                if isinstance(a, ast.Name):
                    out.append((modname, a.id))
        self._callees = out
        return out

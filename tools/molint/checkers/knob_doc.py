"""knob-doc: every `MO_*` env knob is documented, every documented
knob is alive.

The engine's operational surface is its `MO_*` environment knobs, and
the README's knob tables are the single inventory operators work from.
Two rot modes, both silent: a new read site ships without a table row
(the knob is undiscoverable — someone re-implements it under a second
name), and a table row outlives its last read site (operators tune a
dead knob and see nothing).  This rule closes the loop both ways:

  * every `MO_[A-Z0-9_]+` knob READ under `matrixone_tpu/` or the
    configured extra source dirs (`tools/` by default) must appear in
    a README knob-table row (a markdown table line containing the
    knob name);
  * every knob documented in a README table row must have a live read
    site somewhere in the scanned corpus (sources + tests +
    `extra_driver_paths`, bench.py by default) — corpus-global, so it
    skips itself on partial scans exactly like metric-hygiene's
    dead-metric sub-rule.

A "read" is a string literal naming the knob passed to
`os.environ.get/pop/setdefault`, `os.getenv`, an `os.environ[...]`
subscript, or an `env_*`/`_env_*` helper (utils/lru.env_entries,
serving's `_env_int`).  Docstring/comment mentions do not count — they
are documentation, not reads.

Findings in extra source dirs honor the standard suppression comment
syntax (`# molint: disable=knob-doc -- why`, justification required)
even though those files are outside the default scan roots; dead-knob
findings anchor at the README table row and are fixed by deleting the
row (or resurrecting the knob), not suppressed.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Tuple

from tools.molint import Checker, Finding, Project, PyModule
from tools.molint.astutil import dotted

_KNOB_RE = re.compile(r"^MO_[A-Z0-9_]+$")
_DOC_ROW_RE = re.compile(r"MO_[A-Z0-9_]+")

#: call terminals that consume a knob-name string literal
_ENV_GETTERS = {"get", "pop", "setdefault"}
_HELPER_RE = re.compile(r"^_?env")


def _knob_reads(mod: PyModule) -> List[Tuple[str, int]]:
    """(knob, lineno) for every env-knob read in one module."""
    out: List[Tuple[str, int]] = []
    if mod.tree is None:
        return out
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Subscript):
            recv = dotted(node.value) or ""
            if recv.split(".")[-1] != "environ":
                continue
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                          str) \
                    and _KNOB_RE.match(sl.value):
                out.append((sl.value, node.lineno))
            continue
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        parts = d.split(".")
        term = parts[-1]
        env_call = (
            (term in _ENV_GETTERS and len(parts) >= 2
             and parts[-2] == "environ")
            or term == "getenv"
            or _HELPER_RE.match(term) is not None)
        if not env_call:
            continue
        args = list(node.args) + [kw.value for kw in node.keywords]
        for a in args[:2]:       # knob name is arg 0 (or 1 for odd
            #                      helpers); defaults never match MO_*
            if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                    and _KNOB_RE.match(a.value):
                out.append((a.value, node.lineno))
                break
    return out


def _suppressed(mod: PyModule, rule: str, lineno: int) -> bool:
    """Suppression check for modules OUTSIDE the project scan roots
    (project-module findings ride the standard pipeline instead)."""
    for s in mod.suppressions:
        if s.justification and s.covers(rule, lineno):
            s.used = True
            return True
    return False


def _documented(readme_path: str) -> Dict[str, int]:
    """knob -> first README table-row line documenting it."""
    out: Dict[str, int] = {}
    try:
        with open(readme_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    for i, line in enumerate(lines, 1):
        if not line.lstrip().startswith("|"):
            continue
        for m in _DOC_ROW_RE.finditer(line):
            out.setdefault(m.group(0), i)
    return out


class KnobDocChecker(Checker):
    rule = "knob-doc"
    description = ("every MO_* env knob read has a README knob-table "
                   "row, and every documented knob has a live read "
                   "site")
    default_config = {
        #: the knob inventory, root-relative
        "readme": "README.md",
        #: extra source dirs whose reads must be documented (root-
        #: relative; scanned in addition to the project modules)
        "extra_src_dirs": ("tools",),
        #: root-relative files whose reads count as LIVE sites only
        #: (not required to be documented — the bench harness reads
        #: its own private knobs)
        "extra_driver_paths": ("bench.py",),
        #: None = follow project.complete (the dead-knob sub-rule
        #: needs the full corpus; fixture tests force True)
        "corpus_complete": None,
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        readme_rel = config["readme"]
        readme_path = readme_rel if os.path.isabs(readme_rel) \
            else os.path.join(project.root, readme_rel)
        documented = _documented(readme_path)
        findings: List[Finding] = []

        extra_mods: List[PyModule] = []
        for rel in config.get("extra_src_dirs", ()):
            base = rel if os.path.isabs(rel) \
                else os.path.join(project.root, rel)
            if os.path.isfile(base):
                extra_mods.append(PyModule(base, self._rel(project,
                                                           base)))
                continue
            from tools.molint import SKIP_DIRS
            for dirpath, dirs, files in os.walk(base):
                dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        ap = os.path.join(dirpath, fn)
                        extra_mods.append(
                            PyModule(ap, self._rel(project, ap)))
        driver_mods: List[PyModule] = []
        for rel in config.get("extra_driver_paths", ()):
            ap = rel if os.path.isabs(rel) \
                else os.path.join(project.root, rel)
            if os.path.isfile(ap):
                driver_mods.append(PyModule(ap, self._rel(project, ap)))

        live: Dict[str, Tuple[str, int]] = {}

        # project modules: findings ride the standard suppression path
        for mod in project.modules:
            for knob, lineno in _knob_reads(mod):
                live.setdefault(knob, (mod.path, lineno))
                if knob not in documented:
                    findings.append(Finding(
                        self.rule, mod.path, lineno,
                        f"env knob {knob!r} is read here but has no "
                        f"row in a {readme_rel} knob table — document "
                        f"it (default + one-line meaning)"))
        # extra source dirs: suppressions handled locally
        for mod in extra_mods:
            for knob, lineno in _knob_reads(mod):
                live.setdefault(knob, (mod.path, lineno))
                if knob not in documented \
                        and not _suppressed(mod, self.rule, lineno):
                    findings.append(Finding(
                        self.rule, mod.path, lineno,
                        f"env knob {knob!r} is read here but has no "
                        f"row in a {readme_rel} knob table — document "
                        f"it (default + one-line meaning)"))
        # tests + drivers: live-site evidence only
        for mod in list(project.test_modules) + driver_mods:
            for knob, _lineno in _knob_reads(mod):
                live.setdefault(knob, (mod.path, _lineno))

        complete = config.get("corpus_complete")
        if complete is None:
            complete = project.complete
        if complete and documented:
            for knob, lineno in sorted(documented.items()):
                if knob not in live:
                    findings.append(Finding(
                        self.rule, readme_rel, lineno,
                        f"documented knob {knob!r} has no live read "
                        f"site anywhere in the corpus — delete the "
                        f"table row or resurrect the knob"))
        return findings

    @staticmethod
    def _rel(project: Project, abspath: str) -> str:
        rel = os.path.relpath(abspath, project.root)
        return abspath if rel.startswith("..") else rel

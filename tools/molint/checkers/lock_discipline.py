"""lock-discipline: locks are `with`-scoped, the commit lock never
covers blocking I/O, and the static lock-order graph is acyclic.

Three sub-rules:

  * **with-scoping** — `<something>lock.acquire()` outside a `with`
    statement leaks the lock on any exception path between acquire and
    release.  Receivers are matched by name (terminal attribute/name
    containing "lock" or "mutex", case-insensitive), so condition
    variables and admission tickets are out of scope.
  * **no blocking under `_commit_lock`** — the engine commit lock
    serializes every writer and the logtail apply path; a network call
    under it turns one slow peer into a cluster-wide write stall.
    Flagged inside any `with *._commit_lock:` body: socket operations,
    RPC-fabric/worker client calls, `time.sleep`, and blob-frame
    send/recv helpers.  `wal.append` is deliberately ABSENT from the
    denylist (there is no per-function exemption mechanism): WAL-then-
    apply under one critical section IS the commit protocol, and adding
    ("append", "wal") to `blocking_attrs` would flag `Engine.commit_txn`
    itself; the quorum WAL's blocking is bounded by the deadline
    conventions instead.
  * **lock-order graph** — every lexically nested `with lockA: ...
    with lockB:` and every `with lockA:` body calling a same-project
    function that acquires lockB contributes an edge A→B.  A cycle in
    that graph is a potential deadlock even if today's schedules never
    interleave.  Lock identity: `_commit_lock` is normalized to the one
    engine commit lock regardless of receiver; other `self._x` locks
    are class-qualified; module-level locks are module-qualified.
    Same-identity nesting is ignored (RLock re-entry is a supported
    pattern here — `_commit_lock` is an RLock by design).

    **Runtime-edge reconciliation (mosan handshake)**: when
    `tools/molint/observed_lock_edges.json` exists — the dynamic edge
    set exported by the runtime sanitizer (matrixone_tpu/utils/san.py;
    regenerate with `MO_SAN_EXPORT=1 python -m pytest`) — the cycle
    check runs over the UNION of static and observed edges.  The san
    factories name locks with the same identity scheme this checker
    normalizes to ("Class._attr" / dotted module path), so a lexical
    guess that contradicts a real schedule (static A→B, observed B→A)
    closes a mixed cycle and fails the gate, with each edge labeled by
    the side that saw it.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import (FuncInfo, aliases_of, dotted,
                                  iter_functions, walk_skip_nested_funcs)

_LOCKISH = ("lock", "mutex")


def _lock_identity(expr: ast.AST, classname: Optional[str],
                   modname: str) -> Optional[str]:
    """Normalized lock id for a with-item context expr, or None when the
    expr doesn't look like a lock."""
    d = dotted(expr)
    if d is None:
        return None
    term = d.split(".")[-1]
    if not any(k in term.lower() for k in _LOCKISH):
        return None
    if term == "_commit_lock":
        return "Engine._commit_lock"     # one engine-wide commit lock
    parts = d.split(".")
    if parts[0] == "self" and len(parts) == 2:
        return f"{classname or modname}.{term}"
    if len(parts) == 1:                   # module-level lock object
        return f"{modname}.{term}"
    # foreign attribute (other._lock): receiver identity is unknown
    # statically — keep it distinct per receiver name
    return f"?{parts[-2]}.{term}"


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = ("with-scoped acquires, no blocking calls under the "
                   "commit lock, acyclic static lock-order graph")
    default_config = {
        #: method names that block on the network/disk when called under
        #: the commit lock (matched on the call's terminal attr together
        #: with a receiver-name hint, or bare function names)
        "blocking_attrs": (
            ("sendall", None), ("recv", None),
            ("create_connection", None), ("settimeout", None),
            ("sleep", "time"),
            ("call", "client"), ("call", "rpc"),
            ("run", "worker"), ("run", "client"),
            ("udf_eval", None), ("search_index", None),
        ),
        "blocking_funcs": ("_send_msg", "_recv_msg", "urlopen"),
        #: attribute name identifying the engine commit lock in a
        #: with-item (NB: the wal.append exemption is by OMISSION from
        #: the denylists above, not a function whitelist — see the
        #: module docstring before extending blocking_attrs)
        "commit_lock_name": "_commit_lock",
        #: mosan's exported dynamic edge set, unioned into the cycle
        #: check (path relative to the repo root; missing file = static
        #: graph only; None disables — fixture runs use that)
        "runtime_edges_path": "tools/molint/observed_lock_edges.json",
    }

    # ------------------------------------------------------------ check
    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        findings: List[Finding] = []
        # lock-order edges: id -> {target_id: (path, lineno)}
        edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # (modname, classname-or-None, funcname) -> locks the function
        # acquires anywhere in its body.  Class-qualified on purpose:
        # merging same-named methods of unrelated classes manufactures
        # phantom edges (two `close()`s each taking their own lock must
        # not union into one node that cycles)
        acquires: Dict[Tuple[str, Optional[str], str], Set[str]] = {}
        funcs: List[FuncInfo] = []
        for mod in project.modules:
            if mod.tree is None:
                continue
            funcs.extend(iter_functions(mod))
            findings.extend(self._unscoped_acquires(mod))
        for fi in funcs:
            ids = set()
            for node in walk_skip_nested_funcs(fi.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lid = _lock_identity(item.context_expr,
                                             fi.classname,
                                             fi.module.modname)
                        if lid:
                            ids.add(lid)
            key = (fi.module.modname, fi.classname, fi.name)
            acquires[key] = acquires.get(key, set()) | ids

        for fi in funcs:
            findings.extend(self._scan_func(fi, config, edges, acquires))
        runtime = self._load_runtime_edges(project, config)
        for (a, b), site in runtime.items():
            # observed-at-runtime edges join the graph; a static guess
            # contradicted by a real schedule closes a mixed cycle
            edges.setdefault(a, {}).setdefault(b, site)
        findings.extend(self._cycles(edges))
        return findings

    # ---------------------------------------------- mosan runtime edges
    @staticmethod
    def _load_runtime_edges(project: Project, config: dict):
        out = {}
        rel = config.get("runtime_edges_path")
        if not rel:
            return out
        path = rel if os.path.isabs(rel) else os.path.join(project.root,
                                                           rel)
        if not os.path.exists(path):
            return out
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return out          # unreadable export: static graph only
        for e in payload.get("edges", []):
            a, b = e.get("from"), e.get("to")
            if a and b and a != b:
                # findings anchor at the export file, line 1: the real
                # acquisition site lives in the edge's "site" field
                out[(a, b)] = (rel, 1)
        return out

    # ----------------------------------------------- unscoped .acquire
    def _unscoped_acquires(self, mod) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            recv = dotted(node.func.value) or ""
            term = recv.split(".")[-1].lower()
            if not any(k in term for k in _LOCKISH):
                continue
            yield Finding(
                self.rule, mod.path, node.lineno,
                f"explicit {recv}.acquire() — use `with {recv}:` so "
                f"every exception path releases the lock")

    # --------------------------------------- per-function with-analysis
    def _scan_func(self, fi: FuncInfo, config: dict,
                   edges, acquires) -> Iterable[Finding]:
        mod = fi.module
        aliases = aliases_of(mod)
        commit_name = config["commit_lock_name"]
        blocking_attrs = tuple(config["blocking_attrs"])
        blocking_funcs = set(config["blocking_funcs"])

        def record_edge(a: str, b: str, lineno: int):
            if a == b:
                return
            tgt = edges.setdefault(a, {})
            tgt.setdefault(b, (mod.path, lineno))

        def resolve_call_acquires(call: ast.Call) -> Set[str]:
            """Locks acquired by a directly-called project function
            (one hop): `self.f()` -> the caller's own class, bare
            `f()` -> a module-level function, `mod.f()` -> a module-
            level function of an imported project module."""
            d = dotted(call.func)
            if d is None:
                return set()
            parts = d.split(".")
            name = parts[-1]
            if parts[0] == "self" and len(parts) == 2:
                return acquires.get(
                    (mod.modname, fi.classname, name), set())
            if len(parts) == 1:
                return acquires.get((mod.modname, None, name), set())
            # imported project module: mod_alias.func
            target = aliases.get(parts[0])
            if target and len(parts) == 2:
                got = acquires.get((target, None, name))
                if got is not None:
                    return got
                # `from matrixone_tpu import indexing` style: alias maps
                # to the dotted module; try suffix match
                for (mn, cls, fn2), ids in acquires.items():
                    if fn2 == name and cls is None and (
                            mn == target
                            or mn.endswith("." + parts[0])):
                        return ids
            return set()

        def is_blocking(call: ast.Call) -> Optional[str]:
            d = dotted(call.func) or ""
            parts = d.split(".")
            term = parts[-1]
            if term in blocking_funcs and len(parts) <= 2:
                return d
            for attr, hint in blocking_attrs:
                if term != attr or len(parts) < 2:
                    continue
                if hint is None:
                    return d
                recv = ".".join(parts[:-1]).lower()
                if hint in recv:
                    return d
            return None

        findings: List[Finding] = []

        def walk(node: ast.AST, held: Tuple[str, ...],
                 under_commit: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.With):
                    new_held = held
                    commit_here = under_commit
                    for item in child.items:
                        lid = _lock_identity(item.context_expr,
                                             fi.classname, mod.modname)
                        if lid is None:
                            continue
                        # edges from everything already held, INCLUDING
                        # earlier items of this same multi-item with —
                        # `with a, b:` acquires a then b
                        for h in new_held:
                            record_edge(h, lid, child.lineno)
                        new_held = new_held + (lid,)
                        ce = dotted(item.context_expr) or ""
                        if ce.split(".")[-1] == commit_name:
                            commit_here = True
                    walk(child, new_held, commit_here)
                    continue
                if isinstance(child, ast.Call):
                    if under_commit:
                        blocked = is_blocking(child)
                        if blocked:
                            findings.append(Finding(
                                self.rule, mod.path, child.lineno,
                                f"blocking call {blocked}(...) under "
                                f"the commit lock — one slow peer "
                                f"stalls every writer"))
                    if held:
                        for lid in resolve_call_acquires(child):
                            for h in held:
                                record_edge(h, lid, child.lineno)
                walk(child, held, under_commit)

        walk(fi.node, (), False)
        return findings

    # ------------------------------------------------------ cycle check
    def _cycles(self, edges) -> Iterable[Finding]:
        state: Dict[str, int] = {}      # 0 visiting, 1 done
        reported: Set[frozenset] = set()

        def dfs(n: str, stack: List[str]):
            state[n] = 0
            stack.append(n)
            for m in sorted(edges.get(n, {})):
                if state.get(m) == 0:
                    cyc = stack[stack.index(m):] + [m]
                    key = frozenset(cyc)
                    if key not in reported:
                        reported.add(key)
                        path, lineno = edges[n][m]
                        yield Finding(
                            self.rule, path, lineno,
                            "lock-order cycle: "
                            + " -> ".join(cyc)
                            + " — acquisition orders can deadlock")
                elif m not in state:
                    yield from dfs(m, stack)
            stack.pop()
            state[n] = 1

        for n in sorted(edges):
            if n not in state:
                yield from dfs(n, [])

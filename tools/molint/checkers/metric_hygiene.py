"""metric-hygiene: the mo_* metric namespace is registered exactly once,
centrally, with stable label sets.

Conventions encoded (utils/metrics.py is the single registry):

  * every `REGISTRY.counter/gauge/histogram("mo_...")` call lives in the
    registry module — an inline registration elsewhere creates a second
    source of truth for help text and makes the dashboard inventory
    ungreppable;
  * a metric name is registered exactly once, and matches
    `mo_[a-z0-9_]+`;
  * every registered metric is actually driven somewhere (a registered-
    but-never-incremented gauge reads as a healthy zero on dashboards —
    dead metrics mislead);
  * label VALUES passed to .inc()/.set()/.observe() are literals or
    pre-bound names, never inline f-strings/format calls (an f-string
    label is unbounded cardinality at one call site, invisible in the
    registry);
  * one metric keeps ONE label key set across all its call sites —
    prometheus series with differing label sets under a name silently
    fork the time series.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Tuple

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import dotted, first_arg_str

_KINDS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"^mo_[a-z0-9_]+$")
#: positional/keyword args to inc/set/observe that are the VALUE,
#: not labels
_VALUE_KW = {"value", "v"}


def _registration_calls(tree) -> List[Tuple[ast.Call, str, str]]:
    """(call, kind, var) for every REGISTRY.<kind>(...) call; var is the
    assigned module-level name or '' for inline use."""
    out = []
    consumed = set()        # Call nodes owned by an Assign we also walk
    for node in ast.walk(tree):
        target = ""
        call = None
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            call = node.value
            consumed.add(id(call))
            if len(node.targets) == 1 and isinstance(node.targets[0],
                                                     ast.Name):
                target = node.targets[0].id
        elif isinstance(node, ast.Call):
            if id(node) in consumed:
                continue
            call = node
        if call is None or not isinstance(call.func, ast.Attribute):
            continue
        if call.func.attr not in _KINDS:
            continue
        recv = dotted(call.func.value) or ""
        if not recv.split(".")[-1] == "REGISTRY" and recv != "self":
            # only the canonical registry object counts; method defs on
            # the Registry class itself (self.counter) are the factory
            continue
        if recv == "self":
            continue
        out.append((node if target else call, call.func.attr, target))
    return out


class MetricHygieneChecker(Checker):
    rule = "metric-hygiene"
    description = ("mo_* metrics registered exactly once in the registry "
                   "module, driven somewhere, literal label sets")
    default_config = {
        #: path suffix identifying the single registry module
        "registry_suffix": "utils/metrics.py",
        #: metric names allowed to be registered without a module-level
        #: var (none today)
        "allow_inline": (),
        #: root-relative files OUTSIDE the scan roots whose call sites
        #: still count as "driving" a metric (the bench harness fills
        #: the diagnostic stage counters)
        "extra_driver_paths": ("bench.py",),
        #: None = follow project.complete; the dead-metric check needs
        #: the FULL driver corpus, so a partial scan skips it (fixture
        #: tests force True)
        "corpus_complete": None,
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        reg_mod = project.module_by_suffix(config["registry_suffix"])
        findings: List[Finding] = []
        registered: Dict[str, Tuple[str, int, str]] = {}  # name->(path,line,var)
        var_names: Dict[str, str] = {}                    # var -> metric name
        if reg_mod is not None and reg_mod.tree is not None:
            for node, kind, var in _registration_calls(reg_mod.tree):
                call = node.value if isinstance(node, ast.Assign) else node
                name = first_arg_str(call)
                if name is None:
                    findings.append(Finding(
                        self.rule, reg_mod.path, node.lineno,
                        "metric name must be a string literal"))
                    continue
                if not _NAME_RE.match(name):
                    findings.append(Finding(
                        self.rule, reg_mod.path, node.lineno,
                        f"metric name {name!r} does not match "
                        f"mo_[a-z0-9_]+"))
                if name in registered:
                    findings.append(Finding(
                        self.rule, reg_mod.path, node.lineno,
                        f"metric {name!r} registered twice (first at "
                        f"line {registered[name][1]})"))
                else:
                    registered[name] = (reg_mod.path, node.lineno, var)
                if var:
                    var_names[var] = name
                elif name not in config["allow_inline"]:
                    findings.append(Finding(
                        self.rule, reg_mod.path, node.lineno,
                        f"metric {name!r} registered without a module-"
                        f"level variable (callers cannot drive it)"))

        # ---- scan every other module: stray registrations, label
        # hygiene, and which metric vars are actually driven
        driven: Dict[str, bool] = {v: False for v in var_names}
        label_sets: Dict[str, Dict[frozenset, Tuple[str, int]]] = {}
        import os

        from tools.molint import PyModule
        extra_mods = []
        for rel in config.get("extra_driver_paths", ()):
            ap = os.path.join(project.root, rel)
            if os.path.isfile(ap):
                extra_mods.append(PyModule(ap, rel))
        for mod in list(project.modules) + extra_mods:
            if mod.tree is None:
                continue
            is_extra = mod in extra_mods   # drive-detection only
            in_registry = reg_mod is not None and mod.path == reg_mod.path
            if not in_registry and not is_extra:
                for node, kind, var in _registration_calls(mod.tree):
                    call = node.value if isinstance(node, ast.Assign) \
                        else node
                    name = first_arg_str(call) or "?"
                    findings.append(Finding(
                        self.rule, mod.path, node.lineno,
                        f"metric {name!r} registered outside the "
                        f"registry module ({config['registry_suffix']}) "
                        f"— register it there and import the variable"))
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                if node.func.attr not in ("inc", "set", "observe",
                                          "time"):
                    continue
                recv = dotted(node.func.value) or ""
                term = recv.split(".")[-1]
                if term not in var_names:
                    continue
                if not in_registry:
                    driven[term] = True
                if is_extra:
                    continue
                # label literalness + key-set stability
                keys = []
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in _VALUE_KW:
                        continue
                    keys.append(kw.arg)
                    v = kw.value
                    if isinstance(v, ast.JoinedStr):
                        findings.append(Finding(
                            self.rule, mod.path, node.lineno,
                            f"f-string label value for "
                            f"{var_names[term]!r}.{kw.arg} — bind the "
                            f"value to a name first (label cardinality "
                            f"must be auditable)"))
                    elif isinstance(v, ast.Call) and \
                            isinstance(v.func, ast.Attribute) and \
                            v.func.attr == "format":
                        findings.append(Finding(
                            self.rule, mod.path, node.lineno,
                            f".format() label value for "
                            f"{var_names[term]!r}.{kw.arg}"))
                if node.func.attr in ("inc", "set", "observe"):
                    ks = frozenset(keys)
                    seen = label_sets.setdefault(var_names[term], {})
                    if ks not in seen:
                        seen[ks] = (mod.path, node.lineno)

        for metric, sets in sorted(label_sets.items()):
            if len(sets) > 1:
                detail = "; ".join(
                    f"{{{','.join(sorted(ks)) or 'no labels'}}} at "
                    f"{p}:{ln}" for ks, (p, ln) in sorted(
                        sets.items(), key=lambda kv: kv[1]))
                path, lineno = sorted(sets.values())[0]
                findings.append(Finding(
                    self.rule, path, lineno,
                    f"metric {metric!r} driven with differing label "
                    f"key sets ({detail}) — series fork silently"))
        complete = config.get("corpus_complete")
        if complete is None:
            complete = project.complete
        for var, used in sorted(driven.items()):
            if not used and reg_mod is not None and complete:
                name = var_names[var]
                path, lineno, _ = registered[name]
                findings.append(Finding(
                    self.rule, path, lineno,
                    f"metric {name!r} ({var}) is registered but never "
                    f"driven by any .inc/.set/.observe call site — dead "
                    f"gauges mislead dashboards"))
        return findings

"""san-adoption: lockish objects come from the `san` factories.

The runtime concurrency sanitizer (matrixone_tpu/utils/san.py) can only
watch locks built through `san.lock()` / `san.rlock()` /
`san.condition()` — a raw `threading.Lock()` is invisible to the
held-lock stacks, the dynamic lock-order graph and the write auditor.
This rule keeps new code from silently opting out: any
`threading.Lock()`, `threading.RLock()` or `threading.Condition()`
constructed inside `matrixone_tpu/` (outside utils/san.py itself, which
wraps the primitives) is a finding.  `threading.Event`/`Semaphore` are
not lock-order participants and stay free.

Aliased forms are caught too: `import threading as t; t.Lock()` and
`from threading import Lock; Lock()`.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import aliases_of, dotted

_LOCKISH = {"Lock": "san.lock", "RLock": "san.rlock",
            "Condition": "san.condition"}


class SanAdoptionChecker(Checker):
    rule = "san-adoption"
    description = ("threading.Lock/RLock/Condition must come from the "
                   "san factories so the runtime sanitizer sees them")
    default_config = {
        #: files allowed to touch the raw primitives (path suffixes)
        "exempt_suffixes": ("utils/san.py",),
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        exempt = tuple(config["exempt_suffixes"])
        for mod in project.modules:
            if mod.tree is None:
                continue
            if any(mod.path.endswith(sfx) for sfx in exempt):
                continue
            aliases = aliases_of(mod)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._raw_lockish(node, aliases)
                if kind is not None:
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"raw threading.{kind}() is invisible to the "
                        f"runtime sanitizer — use "
                        f"{_LOCKISH[kind]}(\"<Class>._<attr>\") "
                        f"(matrixone_tpu/utils/san.py)")

    @staticmethod
    def _raw_lockish(call: ast.Call, aliases) -> str:
        d = dotted(call.func)
        if d is None:
            return None
        parts = d.split(".")
        term = parts[-1]
        if term not in _LOCKISH:
            return None
        if len(parts) == 1:
            # bare Lock(): only when imported from threading
            target = aliases.get(term, "")
            return term if target == f"threading.{term}" else None
        recv = aliases.get(parts[0], parts[0])
        return term if recv == "threading" else None

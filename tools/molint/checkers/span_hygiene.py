"""span-hygiene: motrace spans are balanced and trace propagation stays
single-definition.

The tracing plane (matrixone_tpu/utils/motrace.py) keeps the ambient
context stack consistent by construction — but only if every span goes
through the context-manager protocol and every wire hop goes through
the fabric.  Conventions encoded:

  * spans open ONLY via the `with` statement: a span factory call
    (`motrace.span(...)`, `statement_span(...)`, `root_span(...)`)
    anywhere but the context expression of a `with` item — assigned to
    a name, passed as an argument, a bare expression statement, or an
    explicit `.__enter__()` — risks an unbalanced enter/exit that
    corrupts the ambient context stack for every later span on the
    thread (`remote_session` is exempt: its object carries
    `attach()`/`harvest()` by design and is still entered via `with`);
  * trace injection is single-definition, exactly like the deadline
    checker's contract for `deadline_ms`: `RpcClient.call` /
    `WorkerClient.run` inject the ambient context themselves, so every
    call site threads trace ctx BY CONSTRUCTION.  Calling
    `motrace.inject(...)`/`motrace.merge_remote(...)` outside the
    fabric modules forks that propagation path, and a hand-built
    `"trace"` key in a header dict passed to `.call(`/`.run(` clobbers
    the fabric's injection with a stale/foreign context.

Suppress with `# molint: disable=span-hygiene -- why` (justification
required) for the rare deliberate exception.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from tools.molint import Checker, Finding, Project
from tools.molint.astutil import aliases_of, dotted

_MOTRACE_MOD = "matrixone_tpu.utils.motrace"


def _span_call_names(mod, factories) -> Set[str]:
    """Local dotted prefixes that resolve to motrace span factories in
    this module: 'motrace.span', '_mt.root_span', bare 'span', ..."""
    out: Set[str] = set()
    for alias, target in aliases_of(mod).items():
        if target == _MOTRACE_MOD or target.endswith(".motrace"):
            for f in factories:
                out.add(f"{alias}.{f}")
        for f in factories:
            if target == f"{_MOTRACE_MOD}.{f}":
                out.add(alias)
    return out


def _injector_names(mod) -> Set[str]:
    out: Set[str] = set()
    for alias, target in aliases_of(mod).items():
        if target == _MOTRACE_MOD or target.endswith(".motrace"):
            out.add(f"{alias}.inject")
            out.add(f"{alias}.merge_remote")
        if target in (f"{_MOTRACE_MOD}.inject",
                      f"{_MOTRACE_MOD}.merge_remote"):
            out.add(alias)
    return out


class SpanHygieneChecker(Checker):
    rule = "span-hygiene"
    description = ("motrace spans open only via `with`; trace injection "
                   "stays in the RPC fabric (rpc.call / WorkerClient.run "
                   "thread ctx by construction)")
    default_config = {
        #: factory functions whose result must be entered immediately
        "factories": ("span", "statement_span", "root_span"),
        #: modules allowed to construct/inject spans freely (the tracer
        #: itself and the two fabric client definitions)
        "fabric_modules": ("utils/motrace.py", "cluster/rpc.py",
                           "worker/client.py"),
    }

    def check(self, project: Project, config: dict) -> Iterable[Finding]:
        factories = tuple(config["factories"])
        fabric = tuple(config["fabric_modules"])
        for mod in project.modules:
            if mod.tree is None:
                continue
            if any(mod.path.endswith(m) for m in fabric):
                continue
            # NOTE: modules without motrace imports still get scanned —
            # the hand-built "trace" wire-key check below is independent
            # of any import
            span_names = _span_call_names(mod, factories)
            inject_names = _injector_names(mod)
            with_exprs = set()
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        with_exprs.add(id(item.context_expr))
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func) or ""
                if d in span_names and id(node) not in with_exprs:
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"span factory {d}(...) used outside a `with` "
                        f"statement — an unbalanced enter/exit corrupts "
                        f"the ambient trace-context stack; open spans "
                        f"only as `with {d}(...):`")
                if d in inject_names:
                    yield Finding(
                        self.rule, mod.path, node.lineno,
                        f"{d}(...) outside the RPC fabric — trace "
                        f"injection is single-definition (RpcClient."
                        f"call / WorkerClient.run thread the ambient "
                        f"ctx for every call site); route the hop "
                        f"through the fabric instead")
                # hand-built "trace" wire keys clobber fabric injection
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("call", "run"):
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Dict) and any(
                                isinstance(k, ast.Constant)
                                and k.value == "trace"
                                for k in arg.keys):
                            yield Finding(
                                self.rule, mod.path, arg.lineno,
                                "hand-built \"trace\" key in a wire "
                                "header — the fabric injects the "
                                "ambient trace ctx itself; a literal "
                                "key ships a stale/foreign context")

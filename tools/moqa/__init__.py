"""moqa — differential query-equivalence analyzer.

The third analysis leg next to molint (static invariants, PR 6) and
mosan (runtime concurrency, PR 8): *result correctness*.  The engine's
whole architecture stakes on one invariant — every execution
configuration (fused vs per-operator, cached vs cold, sharded vs
local, jit vs row UDF tier, materialized view vs base query) returns
the SAME answer — and moqa is the machine that attacks it:

  * a deterministic seeded generator of schemas/data/queries biased
    toward the engine's fusable shapes (tools/moqa/generator.py);
  * metamorphic oracles needing no external truth — TLP, NoREC
    cardinality, LIMIT/OFFSET algebra — plus a sqlite differential
    oracle where types allow (tools/moqa/oracles.py);
  * a config-lattice lockstep runner diffing row-sets exactly across
    nine configuration pairs (tools/moqa/runner.py);
  * an armed padding-canary mode (matrixone_tpu/utils/qa.py) that
    poisons the padded tail of every device buffer and audits results
    and aggregate carries;
  * an automatic reducer that shrinks any failing (schema, data,
    query, config-pair) to a minimal ready-to-paste regression test
    (tools/moqa/reducer.py);
  * planted-bug drills re-introducing two historical bug classes to
    prove the net catches (tools/moqa/plants.py).

Gates: tests/test_moqa.py runs the bounded deterministic corpus in
tier-1 (zero findings fails the build — same contract as molint and
mosan); `python -m tools.precheck --qa-smoke` is the CI one-shot;
`mo_ctl('qa','status'|'run:<seed>')` is the ops surface.  Knobs
(README "Differential testing"): MO_QA_SEED, MO_QA_QUERIES,
MO_QA_SECS, MO_QA_CANARY.
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Tuple

from tools.moqa import oracles, plants, reducer, runner
from tools.moqa.generator import Generator
from tools.moqa.runner import PAIR_NAMES, run_corpus


def corpus_seed(default: int = 2026) -> int:
    """MO_QA_SEED: the tier-1 corpus seed."""
    try:
        return int(os.environ.get("MO_QA_SEED", "") or default)
    except ValueError:
        return default


def corpus_queries(default: int = 85) -> int:
    """MO_QA_QUERIES: generated queries per (non-vector) scenario.
    85 keeps the tier-1 gate above its 300-query floor (3 mixed
    scenarios x 85 + join 42 + vector 17 = 314) while fitting the
    suite in the single-core tier-1 time budget; raise via env for
    deeper sweeps."""
    try:
        return int(os.environ.get("MO_QA_QUERIES", "") or default)
    except ValueError:
        return default


def extended_seconds() -> float:
    """MO_QA_SECS: >0 unlocks the longer randomized multi-seed run."""
    try:
        return float(os.environ.get("MO_QA_SECS", "") or 0.0)
    except ValueError:
        return 0.0


# =====================================================================
# single-case replay — the repro primitive every reduced regression
# test calls (and the reducer probes with)
# =====================================================================

def replay(create: str, insert: str, query: str, pair: str = "fusion",
           setup: Tuple[str, ...] = (), ordered: bool = False,
           partition: Optional[str] = None) -> List[str]:
    """Replay one (schema, data, query) case under one config pair or
    oracle on a fresh in-memory engine.  Returns formatted findings
    (empty list == the invariant held).  `pair` is a runner pair name
    or `oracle:tlp` / `oracle:norec` / `oracle:limit`."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.utils import qa

    R = runner

    def build():
        eng = Engine()
        s = Session(catalog=eng)
        s.execute(create)
        if insert.strip():
            s.execute(insert)
        for ddl in setup:
            s.execute(ddl)
        s.execute("select mo_ctl('serving', 'plan:off')")
        return s

    def rows_of(s, sql):
        return s.execute(sql).rows()

    out: List[str] = []

    if pair.startswith("oracle:"):
        oracle = pair.split(":", 1)[1]
        with R.env_scope(R.ENV_BASELINE):
            s = build()
            try:
                d = _replay_oracle(oracle, s, query, partition,
                                   ddl=(create, insert))
            finally:
                s.close()
        if d is not None:
            out.append(f"[oracle-{oracle}] {query}: {d}")
        return out

    if pair not in R.PAIR_ENV:
        raise ValueError(f"unknown pair {pair!r}; use "
                         f"{sorted(R.PAIR_ENV)} or oracle:<name>")

    with R.env_scope(R.ENV_BASELINE):
        s = build()
        try:
            base = rows_of(s, query)
        finally:
            s.close()

    tol = pair not in R.EXACT_PAIRS
    detail = None
    if pair == "canary":
        with qa.armed_scope(), qa.capture() as probe, \
                R._pair_scope(pair):
            s = build()
            try:
                got = rows_of(s, query)
            finally:
                s.close()
        detail = oracles.diff_rows(base, got, ordered=ordered)
        for f in probe.findings():
            out.append(f.format())
    elif pair == "mview":
        with R.env_scope(R.ENV_BASELINE):
            s = build()
            try:
                s.execute(f"create materialized view qa_replay_mv as "
                          f"{query}")
                # full-mode views refresh on demand by design; the
                # commutation must hold refreshed either way
                s.execute("select mo_ctl('mview', "
                          "'refresh:qa_replay_mv')")
                got = rows_of(s, "select * from qa_replay_mv")
                detail = oracles.diff_rows(base, got, ordered=False,
                                           tol_floats=True)
            finally:
                s.close()
    elif pair == "cache-stale":
        with R._pair_scope(pair):
            s = build()
            try:
                s.execute("select mo_ctl('serving', 'plan:on')")
                s.execute("select mo_ctl('serving', 'result:on')")
                rows_of(s, query)                       # warm
                # shape-preserving rebuild: same table, same row
                # count and dictionary SIZES, rotated string CONTENT —
                # every compiled/cached artifact keyed on anything
                # weaker than content now serves stale answers
                m = re.search(r"create table\s+(\w+)", create, re.I)
                table = m.group(1) if m else "t"
                s.execute(f"drop table {table}")
                s.execute(create)
                if insert.strip():
                    s.execute(rotate_insert_strings(insert))
                # truth: serving caches disabled AND cleared, unfused
                # path; the process-global fragment compile cache
                # stays as warmed — post-rebuild correctness there is
                # exactly what the content keying must provide
                with R.env_scope(R.ENV_BASELINE):
                    s.execute("select mo_ctl('serving', 'clear')")
                    s.execute("select mo_ctl('serving', 'plan:off')")
                    s.execute("select mo_ctl('serving', 'result:off')")
                    truth = rows_of(s, query)
                    s.execute("select mo_ctl('serving', 'plan:on')")
                got = rows_of(s, query)
                detail = oracles.diff_rows(truth, got, ordered=ordered,
                                           mode="exact")
            finally:
                s.close()
    elif pair in ("plan-cache", "result-cache"):
        with R._pair_scope(pair):
            s = build()
            try:
                which = "plan:on" if pair == "plan-cache" \
                    else "result:on"
                s.execute(f"select mo_ctl('serving', '{which}')")
                rows_of(s, query)
                got = rows_of(s, query)
            finally:
                s.close()
        detail = oracles.diff_rows(base, got, ordered=ordered)
    else:
        with R._pair_scope(pair):
            s = build()
            try:
                got = rows_of(s, query)
            finally:
                s.close()
        detail = oracles.diff_rows(base, got, ordered=ordered,
                                   tol_floats=tol)
    if detail is not None:
        out.append(f"[lockstep-mismatch:{pair}] {query}: {detail}")
    return out


def rotate_insert_strings(insert_sql: str) -> str:
    """Rotate the distinct quoted strings of an INSERT among
    themselves: same count, same dictionary sizes, different content —
    the content-staleness probe (non-string literals untouched)."""
    def plain(s: str) -> bool:
        # leave date/vector literals alone — they are typed values,
        # not dictionary strings
        return not (re.match(r"^\d{4}-\d{2}-\d{2}", s)
                    or s.startswith("["))
    lits = [s for s in re.findall(r"'((?:[^']|'')*)'", insert_sql)
            if plain(s)]
    distinct = sorted(set(lits))
    if len(distinct) < 2:
        distinct = distinct + ["qa_rot"]
    rot = {a: b for a, b in zip(distinct,
                                distinct[1:] + distinct[:1])}
    return re.sub(
        r"'((?:[^']|'')*)'",
        lambda m: "'" + rot.get(m.group(1), m.group(1)) + "'"
        if plain(m.group(1)) else m.group(0),
        insert_sql)


def _replay_oracle(oracle: str, s, query: str,
                   partition: Optional[str],
                   ddl: Tuple[str, str] = ("", "")) -> Optional[str]:
    """Textual oracle replays over a raw SQL string (the reduced-repro
    path; the corpus runner uses the structured versions)."""
    def ex(sql):
        return s.execute(sql).rows()

    if oracle == "tlp":
        if not partition:
            raise ValueError("oracle:tlp needs partition=")
        base = ex(query)
        parts = []
        for br in (partition, f"not ({partition})",
                   f"({partition}) is null"):
            parts.extend(ex(_and_where(query, br)))
        return oracles.diff_rows(base, parts, ordered=False)
    if oracle == "norec":
        if not partition:
            raise ValueError("oracle:norec needs partition=")
        m = re.search(r"\bfrom\s+(\w+)", query, re.I)
        table = m.group(1)
        wm = re.search(r"\bwhere\b(.*?)(?:\bgroup by\b|\border by\b|"
                       r"\blimit\b|$)", query, re.I | re.S)
        where = [wm.group(1).strip()] if wm else []
        return oracles.norec_check(ex, table, partition, where)
    if oracle == "limit":
        lm = re.search(r"\blimit\s+(\d+)(?:\s+offset\s+(\d+))?\s*$",
                       query, re.I)
        if not lm:
            return None
        k = int(lm.group(1))
        off = int(lm.group(2) or 0)
        full = ex(query[:lm.start()].rstrip())
        got = ex(query)
        return oracles.diff_rows(got, full[off:off + k], ordered=True)
    if oracle == "sqlite":
        import sqlite3
        conn = sqlite3.connect(":memory:")
        try:
            for sql in ddl:
                if sql.strip():
                    conn.execute(_sqlite_ddl(sql))
            want = [tuple(r) for r in conn.execute(query).fetchall()]
        finally:
            conn.close()
        got = ex(query)
        ordered = bool(re.search(r"\border by\b", query, re.I))
        return oracles.diff_rows(got, want, ordered=ordered,
                                 mode="xengine")
    raise ValueError(f"unknown oracle {oracle!r}")


def _sqlite_ddl(sql: str) -> str:
    """Translate an engine CREATE/INSERT into sqlite's dialect for the
    mirrorable type subset (int/bigint/double/varchar).  A decimal,
    bool, date or vector column raises — the reducer's probes then
    steer toward dropping the unmirrorable columns."""
    if re.search(r"\b(decimal|numeric|bool|boolean|date|datetime|"
                 r"timestamp|vecf)", sql, re.I) \
            and re.match(r"\s*create\b", sql, re.I):
        raise ValueError("schema has sqlite-unmirrorable columns")
    out = re.sub(r"\bbigint\b|\bint\b|\binteger\b", "integer", sql,
                 flags=re.I)
    out = re.sub(r"\bdouble\b|\bfloat\b", "real", out, flags=re.I)
    out = re.sub(r"\bvarchar\(\d+\)\b", "text", out, flags=re.I)
    return out


def _and_where(query: str, branch: str) -> str:
    m = re.search(r"\bwhere\b", query, re.I)
    if m:
        return _insert_branch(query, m, branch)
    mm = re.search(r"\b(group by|order by|limit)\b", query, re.I)
    at = mm.start() if mm else len(query)
    return f"{query[:at].rstrip()} where ({branch}) {query[at:]}"


def _insert_branch(query: str, where_m, branch: str) -> str:
    tail = re.search(r"\b(group by|order by|limit)\b",
                     query[where_m.end():], re.I)
    end = where_m.end() + (tail.start() if tail else
                           len(query) - where_m.end())
    cond = query[where_m.end():end].strip()
    return (f"{query[:where_m.end()]} ({cond}) and ({branch}) "
            f"{query[end:]}")


# =====================================================================
# smoke + status + CLI
# =====================================================================

def run_smoke(seed: Optional[int] = None) -> dict:
    """The precheck one-shot: a small corpus plus one planted-bug
    drill; <30s on the tier-1 box."""
    seed = corpus_seed() if seed is None else seed
    rep = run_corpus(seed=seed, queries_per_scenario=8,
                     pairs=["fusion", "dense-groups", "plan-cache",
                            "result-cache", "canary", "cache-stale",
                            "narrow-encodings"],
                     reduce_findings=0,
                     oracle_fraction=0.34, stale_fraction=0.25,
                     max_views=2)
    with plants.plant("pad-leak"):
        # a SCALAR sum: the leaky kernels are the scalar/general-path
        # sums; grouped dict keys would ride the dense lanes past them
        caught = replay(
            create="create table qa_pl (v bigint, d double)",
            insert="insert into qa_pl values " + ",".join(
                f"({i}, {i}.25)" for i in range(23)),
            query="select sum(v) sv, sum(d) sd from qa_pl",
            pair="canary")
    rep["plant_caught"] = bool(caught)
    return rep


def last_run_status() -> dict:
    """mo_ctl('qa','status') payload."""
    from matrixone_tpu.utils import qa
    return {"pairs": list(PAIR_NAMES),
            "canary": qa.report(),
            "last_run": runner.last_run()}


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m tools.moqa",
        description="differential query-equivalence analyzer (see "
                    "README 'Differential testing')")
    ap.add_argument("--seed", type=int, default=None,
                    help="corpus seed (default MO_QA_SEED or 2026)")
    ap.add_argument("--queries", type=int, default=None,
                    help="queries per scenario (default MO_QA_QUERIES "
                         "or 110)")
    ap.add_argument("--pairs", default=None,
                    help="comma-separated pair subset "
                         f"(default: all of {','.join(PAIR_NAMES)})")
    ap.add_argument("--secs", type=float, default=None,
                    help="randomized multi-seed run for this many "
                         "seconds (default MO_QA_SECS)")
    ap.add_argument("--smoke", action="store_true",
                    help="the precheck smoke (small corpus + planted "
                         "drill)")
    ap.add_argument("--plant", default=None,
                    choices=plants.plant_names(),
                    help="run the corpus with a planted bug; exit 0 "
                         "iff moqa catches it")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.smoke:
        rep = run_smoke(args.seed)
        print(json.dumps({k: rep[k] for k in
                          ("seed", "queries", "total_checks", "pairs",
                           "seconds", "plant_caught")},
                         sort_keys=True))
        for line in rep["findings_formatted"]:
            print(line)
        ok = not rep["findings"] and rep["plant_caught"]
        return 0 if ok else 1

    seed = corpus_seed() if args.seed is None else args.seed
    nq = corpus_queries() if args.queries is None else args.queries
    pairs = args.pairs.split(",") if args.pairs else None
    secs = extended_seconds() if args.secs is None else args.secs

    def one(seed_i):
        if args.plant:
            with plants.plant(args.plant):
                return run_corpus(seed=seed_i,
                                  queries_per_scenario=nq,
                                  pairs=pairs)
        return run_corpus(seed=seed_i, queries_per_scenario=nq,
                          pairs=pairs)

    import time as _time
    reports = []
    t0 = _time.monotonic()
    s_i = seed
    while True:
        reports.append(one(s_i))
        s_i += 1
        if not secs or _time.monotonic() - t0 >= secs:
            break

    n_findings = sum(len(r["findings"]) for r in reports)
    if args.json:
        print(json.dumps(reports if len(reports) > 1 else reports[0],
                         indent=1, sort_keys=True, default=str))
    else:
        for r in reports:
            for line in r["findings_formatted"]:
                print(line)
            for f in r["findings"]:
                if f.get("repro"):
                    print("\n--- reduced repro "
                          "(paste into tests/) ---")
                    print(f["repro"])
            print(json.dumps({k: r[k] for k in
                              ("seed", "queries", "total_checks",
                               "pairs", "oracle_checks", "seconds")},
                             sort_keys=True))
    if args.plant:
        print("planted bug CAUGHT" if n_findings
              else "planted bug NOT caught", file=sys.stderr)
        return 0 if n_findings else 1
    return 1 if n_findings else 0

import sys

from tools.moqa import main

if __name__ == "__main__":
    sys.exit(main())

"""moqa query/schema/data generator — deterministic, seeded, biased
toward the engine's fusable (and soon-to-be-fusable) shapes.

Everything here is driven by one `numpy.random.default_rng(seed)`:
the same seed always yields the same scenarios, rows and queries, so
the tier-1 corpus is reproducible and any finding names the seed that
produced it.

Scenarios carry their data as host-side python rows (the reducer
shrinks those row lists); queries are structured (`GenQuery`) so the
reducer can drop clauses instead of string-munging SQL.  The bias
knobs the ISSUE names are all here:

  * filters / projections / group-bys / scalar aggregates over
    NULL-heavy bigint, double, DECIMAL, dict-string, bool and date
    columns — the shapes vm/fusion.py traces;
  * ORDER BY (+ deterministic id tiebreak) and LIMIT/OFFSET tails;
  * odd row counts that straddle the padded-batch buckets
    (container/device.bucket_length: ..., 1024, 2048, ...) and sit on
    either side of `MO_FUSION_MIN_ROWS`-style thresholds;
  * a UDF family (CREATE FUNCTION, jit vs row tiers) and a small
    vector family (ivfflat + `MO_IVF_SHARDS`) so those lattice axes
    have queries to disagree on.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


# =====================================================================
# expressions: sql text + metadata the oracles need
# =====================================================================

@dataclasses.dataclass(frozen=True)
class Expr:
    sql: str
    kind: str                    # 'num' | 'str' | 'bool' | 'other'
    cols: frozenset              # referenced column names
    sqlite_ok: bool = True
    features: frozenset = frozenset()


def _e(sql, kind, cols, sqlite_ok=True, features=()):
    return Expr(sql, kind, frozenset(cols), sqlite_ok,
                frozenset(features))


# =====================================================================
# scenarios
# =====================================================================

@dataclasses.dataclass
class ColumnSpec:
    name: str
    sql_type: str
    kind: str          # int | bigint | float | dec | str | bool | date | vec
    sqlite_type: Optional[str]   # None = column not mirrored to sqlite


@dataclasses.dataclass
class Scenario:
    name: str
    table: str
    columns: List[ColumnSpec]
    rows: List[tuple]            # python values, None = NULL
    #: index splitting rows into wave1/wave2 for the mview / staleness
    #: procedures (insert wave1, create view, insert wave2)
    wave_split: int = 0
    #: extra DDL run after CREATE TABLE + first insert (UDFs, indexes)
    setup_sql: List[str] = dataclasses.field(default_factory=list)
    features: frozenset = frozenset()

    # --------------------------------------------------------- rendering
    def create_sql(self) -> str:
        cols = ", ".join(f"{c.name} {c.sql_type}" for c in self.columns)
        return f"create table {self.table} ({cols})"

    def insert_sql(self, rows: Optional[List[tuple]] = None) -> str:
        rows = self.rows if rows is None else rows
        return (f"insert into {self.table} values "
                + ",".join(self.render_row(r) for r in rows))

    def render_row(self, row: tuple) -> str:
        return "(" + ",".join(
            render_literal(v, c.kind)
            for v, c in zip(row, self.columns)) + ")"

    def column(self, name: str) -> ColumnSpec:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)


def render_literal(v, kind: str) -> str:
    if v is None:
        return "null"
    if kind in ("int", "bigint"):
        return str(int(v))
    if kind == "float":
        return repr(float(v))
    if kind == "dec":
        return f"{v:.2f}"
    if kind == "bool":
        return "true" if v else "false"
    if kind == "date":
        return f"date '{v}'"
    if kind == "vec":
        return "'[" + ",".join(f"{x:.3f}" for x in v) + "]'"
    s = str(v).replace("'", "''")
    return f"'{s}'"


# =====================================================================
# queries
# =====================================================================

@dataclasses.dataclass
class GenQuery:
    table: str
    select: List[Tuple[str, str]]          # (expr sql, alias)
    where: List[str] = dataclasses.field(default_factory=list)  # ANDed
    group_by: List[str] = dataclasses.field(default_factory=list)
    order_by: List[str] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    #: equi-join tail: "from table <join_kind> <join_table> on <join_on>"
    join_table: Optional[str] = None
    join_kind: str = "join"
    join_on: Optional[str] = None
    features: frozenset = frozenset()
    cols: frozenset = frozenset()

    def sql(self) -> str:
        items = ", ".join(f"{e} {a}" if a else e for e, a in self.select)
        s = f"select {items} from {self.table}"
        if self.join_table:
            s += f" {self.join_kind} {self.join_table} on {self.join_on}"
        if self.where:
            s += " where " + " and ".join(
                w if len(self.where) == 1 else f"({w})"
                for w in self.where)
        if self.group_by:
            s += " group by " + ", ".join(self.group_by)
        if self.order_by:
            s += " order by " + ", ".join(self.order_by)
        if self.limit is not None:
            s += f" limit {self.limit}"
        if self.offset:
            s += f" offset {self.offset}"
        return s

    def has(self, feat: str) -> bool:
        return feat in self.features

    def clone(self, **patch) -> "GenQuery":
        return dataclasses.replace(self, **patch)


# =====================================================================
# the generator
# =====================================================================

_G_VALUES = ["aa", "bb", "cc", "dd", "ee"]
_S_VALUES = [f"s{i:02d}" for i in range(18)]


class Generator:
    """One seeded stream of scenarios + queries."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)

    # ----------------------------------------------------------- helpers
    def _choice(self, seq):
        return seq[int(self.rng.integers(0, len(seq)))]

    def _maybe(self, p: float) -> bool:
        return float(self.rng.random()) < p

    # --------------------------------------------------------- scenarios
    def scenarios(self, straddle_rows: int = 1027) -> List[Scenario]:
        """The corpus scenarios: mixed small, NULL-heavy, a padded-
        bucket straddler, and a small vector table."""
        out = [
            self.mixed_scenario("qa_small", n_rows=149, null_p=0.12),
            self.mixed_scenario("qa_nulls", n_rows=88, null_p=0.45),
            self.mixed_scenario("qa_pad", n_rows=straddle_rows,
                                null_p=0.10),
            self.join_scenario("qa_join", n_rows=131, null_p=0.25),
            self.vector_scenario("qa_vec", n_rows=72, dim=8),
        ]
        return out

    def join_scenario(self, table: str, n_rows: int,
                      null_p: float) -> Scenario:
        """A mixed main (probe) table plus a NULL-heavy build-side dim
        table `<table>_d`, created through setup_sql so every replay /
        repro path carries it.  Its string key `jg` shares the `g`
        value space (the varchar code-translation path) and its bigint
        key `jk` overlaps `v` WITH duplicates, so probe fan-out, NULL
        keys, and left-join null-extension all occur in the corpus."""
        sc = self.mixed_scenario(table, n_rows=n_rows, null_p=null_p)
        rng = self.rng
        dim = f"{table}_d"
        n_dim = 37
        dim_rows = []
        for j in range(n_dim):
            jg = None if float(rng.random()) < null_p else \
                _G_VALUES[int(rng.integers(0, 5))]
            jk = None if float(rng.random()) < null_p else \
                int(rng.integers(-10, 40))       # dups near v's range
            jv = None if float(rng.random()) < null_p / 2 else \
                int(rng.integers(-50, 200))
            jw = int(rng.integers(0, 6))
            dim_rows.append((j, jg, jk, jv, jw))
        vals = ",".join(
            "(" + ",".join(("null" if x is None
                            else f"'{x}'" if isinstance(x, str)
                            else str(x)) for x in r) + ")"
            for r in dim_rows)
        setup = list(sc.setup_sql) + [
            f"create table {dim} (jid bigint, jg varchar(8), "
            f"jk bigint, jv bigint, jw int)",
            f"insert into {dim} values {vals}",
        ]
        return dataclasses.replace(
            sc, name=table, setup_sql=setup,
            features=sc.features | frozenset({"join_scenario"}))

    def mixed_scenario(self, table: str, n_rows: int,
                       null_p: float) -> Scenario:
        cols = [
            ColumnSpec("id", "bigint", "bigint", "integer"),
            ColumnSpec("g", "varchar(8)", "str", "text"),
            ColumnSpec("s", "varchar(16)", "str", "text"),
            ColumnSpec("v", "bigint", "bigint", "integer"),
            ColumnSpec("w", "int", "int", "integer"),
            ColumnSpec("d", "double", "float", "real"),
            ColumnSpec("q", "decimal(12,2)", "dec", None),
            ColumnSpec("b", "bool", "bool", None),
            ColumnSpec("dt", "date", "date", None),
        ]
        rng = self.rng
        rows = []
        for i in range(n_rows):
            def nul(p=null_p):
                return float(rng.random()) < p
            g = None if nul() else _G_VALUES[int(rng.integers(0, 5))]
            s = None if nul(null_p / 2) else \
                _S_VALUES[int(rng.integers(0, len(_S_VALUES)))]
            v = None if nul() else int(rng.integers(-40, 120))
            w = None if nul() else int(rng.integers(-7, 9))
            # quarters only: exact in binary AND in sqlite REAL, so the
            # cross-engine oracle compares exactly where sums allow
            d = None if nul() else float(int(rng.integers(-40, 80))) / 4
            q = None if nul() else float(int(rng.integers(-9000, 9000))) / 100
            b = None if nul(null_p / 2) else bool(rng.integers(0, 2))
            day = 1 + int(rng.integers(0, 28))
            mon = 1 + int(rng.integers(0, 3))
            dt_ = None if nul(null_p / 2) else f"1995-{mon:02d}-{day:02d}"
            rows.append((i, g, s, v, w, d, q, b, dt_))
        setup = [
            "create function qa_f(x DOUBLE, y BIGINT) returns DOUBLE "
            "language python as $$ x * 2.0 + y $$",
        ]
        return Scenario(name=table, table=table, columns=cols, rows=rows,
                        wave_split=max(1, int(n_rows * 0.7)),
                        setup_sql=setup,
                        features=frozenset({"mixed"}))

    def vector_scenario(self, table: str, n_rows: int,
                        dim: int) -> Scenario:
        cols = [
            ColumnSpec("id", "bigint", "bigint", None),
            ColumnSpec("k", "varchar(4)", "str", None),
            ColumnSpec("emb", f"vecf32({dim})", "vec", None),
        ]
        rng = self.rng
        rows = []
        for i in range(n_rows):
            vec = tuple(round(float(x), 3)
                        for x in rng.normal(0, 1, dim))
            rows.append((i, _G_VALUES[int(rng.integers(0, 3))], vec))
        setup = [f"create index qa_iv using ivfflat on {table} (emb) "
                 f"lists = 4"]
        return Scenario(name=table, table=table, columns=cols, rows=rows,
                        wave_split=n_rows, setup_sql=setup,
                        features=frozenset({"vector"}))

    # ------------------------------------------------------- expressions
    def _num_expr(self, depth: int = 0) -> Expr:
        r = float(self.rng.random())
        if depth >= 2 or r < 0.45:
            col = self._choice(["v", "w", "d", "q", "id"])
            return _e(col, "num", [col], sqlite_ok=col != "q")
        if r < 0.70:
            a, b = self._num_expr(depth + 1), self._num_expr(depth + 1)
            op = self._choice(["+", "-", "*"])
            return _e(f"({a.sql} {op} {b.sql})", "num", a.cols | b.cols,
                      a.sqlite_ok and b.sqlite_ok,
                      a.features | b.features)
        if r < 0.85:
            a = self._num_expr(depth + 1)
            c = int(self.rng.integers(-9, 12))
            op = self._choice(["+", "-", "*"])
            return _e(f"({a.sql} {op} {c})", "num", a.cols, a.sqlite_ok,
                      a.features)
        p = self._pred(depth + 1)
        a, b = self._num_expr(depth + 1), self._num_expr(depth + 1)
        return _e(f"case when {p.sql} then {a.sql} else {b.sql} end",
                  "num", p.cols | a.cols | b.cols,
                  p.sqlite_ok and a.sqlite_ok and b.sqlite_ok,
                  p.features | a.features | b.features | {"case"})

    def _pred(self, depth: int = 0) -> Expr:
        r = float(self.rng.random())
        if depth >= 2 or r < 0.40:
            a = self._num_expr(depth + 1)
            op = self._choice(["<", "<=", ">", ">=", "=", "<>"])
            c = int(self.rng.integers(-30, 90))
            return _e(f"{a.sql} {op} {c}", "bool", a.cols, a.sqlite_ok,
                      a.features)
        if r < 0.52:
            col = self._choice(["g", "s", "v", "d", "b"])
            neg = " not" if self._maybe(0.3) else ""
            return _e(f"{col} is{neg} null", "bool", [col],
                      sqlite_ok=col not in ("b", "dt", "q"))
        if r < 0.64:
            val = self._choice(_G_VALUES)
            op = self._choice(["=", "<>", "<", ">="])
            return _e(f"g {op} '{val}'", "bool", ["g"])
        if r < 0.72:
            pat = self._choice(["a%", "%b", "%c%", "s0%", "_a"])
            neg = "not " if self._maybe(0.25) else ""
            col = self._choice(["g", "s"])
            return _e(f"{col} {neg}like '{pat}'", "bool", [col],
                      features={"like"})
        if r < 0.80:
            vals = sorted({self._choice(_G_VALUES) for _ in range(2)})
            lit = ", ".join(f"'{v}'" for v in vals)
            neg = "not " if self._maybe(0.25) else ""
            return _e(f"g {neg}in ({lit})", "bool", ["g"])
        if r < 0.90:
            a, b = self._pred(depth + 1), self._pred(depth + 1)
            op = self._choice(["and", "or"])
            return _e(f"({a.sql} {op} {b.sql})", "bool", a.cols | b.cols,
                      a.sqlite_ok and b.sqlite_ok,
                      a.features | b.features)
        a = self._pred(depth + 1)
        return _e(f"not ({a.sql})", "bool", a.cols, a.sqlite_ok,
                  a.features)

    def partition_pred(self) -> Expr:
        """A TLP partition predicate: must be three-valued (true / false
        / NULL) over the data, never error."""
        r = float(self.rng.random())
        if r < 0.5:
            col = self._choice(["v", "w", "d"])
            op = self._choice(["<", ">", "<=", ">="])
            c = int(self.rng.integers(-20, 60))
            return _e(f"{col} {op} {c}", "bool", [col])
        if r < 0.75:
            val = self._choice(_G_VALUES)
            return _e(f"g = '{val}'", "bool", ["g"])
        return _e(f"b = true", "bool", ["b"], sqlite_ok=False)

    # ----------------------------------------------------------- queries
    def query(self, scenario: Scenario) -> GenQuery:
        if "vector" in scenario.features:
            return self._vector_query(scenario)
        if "join_scenario" in scenario.features:
            r = float(self.rng.random())
            if r < 0.45:
                return self._join_query(scenario)
            if r < 0.80:
                return self._window_query(scenario)
            # the single-table shapes still run on the probe table
        r = float(self.rng.random())
        if r < 0.42:
            return self._plain_query(scenario)
        if r < 0.58:
            return self._scalar_agg_query(scenario)
        return self._grouped_agg_query(scenario)

    def _join_on(self, sc: Scenario) -> Tuple[str, frozenset]:
        dim = f"{sc.table}_d"
        if self._maybe(0.5):
            # dict-string key: each side's dictionary assigns codes
            # independently — the probe-side code translation path
            return (f"{sc.table}.g = {dim}.jg", frozenset(["g"]))
        return (f"{sc.table}.v = {dim}.jk", frozenset(["v"]))

    def _join_query(self, sc: Scenario) -> GenQuery:
        """Two-table equi-join over NULL-heavy keys: grouped aggregate
        above the probe (the fused probe→agg chain) or a plain
        probe-gather tail with a deterministic total order."""
        dim = f"{sc.table}_d"
        on, oncols = self._join_on(sc)
        kind = "join" if self._maybe(0.65) else "left join"
        feats = {"join"} | ({"left_join"} if kind != "join" else set())
        where, wcols, wfeats, _ = self._where(p=0.55)
        feats |= set(wfeats)
        if self._maybe(0.45):
            select = [("g", "k0"), ("count(*)", "a0"),
                      ("sum(jv)", "a1")]
            if self._maybe(0.5):
                select.append(("sum(v + jw)", "a2"))
            q = GenQuery(table=sc.table, select=select,
                         group_by=["k0"], where=where,
                         join_table=dim, join_kind=kind, join_on=on,
                         cols=oncols | wcols | frozenset(["g", "v"]),
                         features=frozenset(feats | {"agg", "grouped"}))
            return q
        select = [("id", None), ("jid", None), ("v", "c0"),
                  ("jv", "c1")]
        if self._maybe(0.4):
            select.append(("jg", "c2"))
        q = GenQuery(table=sc.table, select=select, where=where,
                     join_table=dim, join_kind=kind, join_on=on,
                     order_by=["id", "jid"],
                     cols=oncols | wcols | frozenset(["id", "v"]),
                     features=frozenset(feats | {"ordered"}))
        if self._maybe(0.4):
            q.limit = int(self.rng.integers(1, 30))
            q.features = q.features | {"limited"}
        return q

    _WIN_FNS = (
        "row_number() over (partition by g order by v, id)",
        "rank() over (partition by g order by v)",
        "dense_rank() over (partition by g order by w)",
        "rank() over (order by v)",
        "ntile(3) over (order by id)",
        "sum(v) over (partition by g)",
        "count(*) over (partition by b)",
        "max(d) over (partition by g)",
        "avg(v) over (partition by g)",
        "min(w) over (partition by s)",
    )

    #: join-output rows can tie on every probe column (duplicate build
    #: matches), so windows OVER a join draw only from the tie-safe
    #: subset — rank/dense_rank and partition aggregates are functions
    #: of the row's VALUES, never of the order among tied rows
    _WIN_FNS_TIE_SAFE = tuple(f for f in _WIN_FNS
                              if not f.startswith(("row_number",
                                                   "ntile")))

    def _window_query(self, sc: Scenario) -> GenQuery:
        """Frame-free rank / partition-aggregate windows, ordered by
        the unique id so the row-set compare is total-order exact;
        sometimes over the join so the window prelude consumes a
        probe-gather tail."""
        feats = {"window", "ordered"}
        joined = self._maybe(0.25)
        fns = self._WIN_FNS_TIE_SAFE if joined else self._WIN_FNS
        select = [("id", None)]
        n_wins = 1 + int(self.rng.integers(0, 2))
        cols = frozenset(["id", "g", "v"])
        for i in range(n_wins):
            select.append((self._choice(fns), f"w{i}"))
        where, wcols, wfeats, _ = self._where(p=0.4)
        feats |= set(wfeats)
        q = GenQuery(table=sc.table, select=select, where=where,
                     order_by=["id"], cols=cols | wcols,
                     features=frozenset(feats))
        if joined:
            on, oncols = self._join_on(sc)
            q.join_table = f"{sc.table}_d"
            q.join_kind = "join" if self._maybe(0.6) else "left join"
            q.join_on = on
            q.select = q.select + [("jid", None)]
            q.order_by = ["id", "jid"]
            q.cols = q.cols | oncols
            q.features = q.features | {"join"}
        return q

    def _where(self, p: float = 0.75) -> Tuple[List[str], frozenset,
                                               frozenset, bool]:
        parts, cols, feats, lite = [], frozenset(), frozenset(), True
        n = 0
        if self._maybe(p):
            n = 1 + int(self._maybe(0.3))
        for _ in range(n):
            w = self._pred()
            parts.append(w.sql)
            cols |= w.cols
            feats |= w.features
            lite = lite and w.sqlite_ok
        return parts, cols, feats, lite

    def _plain_query(self, sc: Scenario) -> GenQuery:
        n_items = 1 + int(self.rng.integers(0, 3))
        select, cols, feats = [], frozenset(), frozenset({"plain"})
        lite = True
        for i in range(n_items):
            r = float(self.rng.random())
            if r < 0.5:
                e = self._num_expr()
            elif r < 0.7:
                col = self._choice(["g", "s", "v", "d", "b", "dt", "id"])
                e = _e(col, "other", [col],
                       sqlite_ok=col not in ("b", "dt"))
            elif r < 0.85:
                p = self._pred()
                e = _e(f"{p.sql}", "bool", p.cols, p.sqlite_ok,
                       p.features)
            else:
                e = _e(f"qa_f(d, id)", "num", ["d", "id"],
                       sqlite_ok=False, features=frozenset({"udf"}))
            select.append((e.sql, f"c{i}"))
            cols |= e.cols
            feats |= e.features
            lite = lite and e.sqlite_ok
        where, wcols, wfeats, wlite = self._where()
        cols |= wcols
        feats |= wfeats
        lite = lite and wlite
        q = GenQuery(table=sc.table, select=select, where=where,
                     cols=cols, features=feats)
        if self._maybe(0.45):
            # deterministic total order: trailing unique-id tiebreak
            keys = [f"c0" if self._maybe(0.5) else "id"]
            if keys[-1] != "id":
                keys.append("id")
            q.order_by = keys
            q.select.append(("id", "oid"))
            q.cols = q.cols | {"id"}
            feats = feats | {"ordered"}
            if self._maybe(0.6):
                q.limit = int(self.rng.integers(1, 40))
                if self._maybe(0.4):
                    q.offset = int(self.rng.integers(1, 20))
                feats = feats | {"limited"}
        if not q.order_by and q.limit is None:
            feats = feats | {"tlp_ok"}
        q.features = frozenset(feats)
        if lite:
            q.features = q.features | {"sqlite_ok"}
        return q

    _AGGS = ["count", "sum", "avg", "min", "max"]

    def _scalar_agg_query(self, sc: Scenario) -> GenQuery:
        n_aggs = 1 + int(self.rng.integers(0, 3))
        select, cols, feats = [], frozenset(), frozenset({"agg"})
        lite = True
        for i in range(n_aggs):
            fn = self._choice(self._AGGS)
            if fn == "count" and self._maybe(0.5):
                e_sql, e_cols, e_lite = "count(*)", frozenset(), True
            else:
                a = self._num_expr()
                e_sql, e_cols, e_lite = f"{fn}({a.sql})", a.cols, \
                    a.sqlite_ok
            select.append((e_sql, f"a{i}"))
            cols |= e_cols
            lite = lite and e_lite
        where, wcols, wfeats, wlite = self._where()
        feats |= wfeats
        q = GenQuery(table=sc.table, select=select, where=where,
                     cols=cols | wcols, features=frozenset(feats))
        if lite and wlite:
            q.features = q.features | {"sqlite_ok"}
        return q

    def _grouped_agg_query(self, sc: Scenario) -> GenQuery:
        keys, kcols, kfeats, klite = [], frozenset(), frozenset(), True
        r = float(self.rng.random())
        if r < 0.55:
            keys = ["g"]
            kcols = frozenset(["g"])
        elif r < 0.72:
            p = self.partition_pred()
            keys = [p.sql]
            kcols, klite = p.cols, p.sqlite_ok
        elif r < 0.88:
            keys = ["g", "b"]
            kcols, klite = frozenset(["g", "b"]), False
        else:
            thr = int(self.rng.integers(0, 40))
            keys = [f"case when v > {thr} then 'hi' else 'lo' end"]
            kcols = frozenset(["v"])
        n_aggs = 1 + int(self.rng.integers(0, 3))
        select = [(k, f"k{i}") for i, k in enumerate(keys)]
        cols, lite = kcols, klite
        maintainable = True
        for i in range(n_aggs):
            fn = self._choice(self._AGGS)
            if fn == "count" and self._maybe(0.5):
                select.append(("count(*)", f"a{i}"))
                continue
            a = self._num_expr()
            select.append((f"{fn}({a.sql})", f"a{i}"))
            cols |= a.cols
            lite = lite and a.sqlite_ok
        where, wcols, wfeats, wlite = self._where(p=0.6)
        cols |= wcols
        feats = {"agg", "grouped"} | set(wfeats) | set(kfeats)
        # mview-maintainable shape: plain single-table group-by; keep it
        # conservative (the planner itself decides — this flag only
        # nominates candidates for the mview commutation pair)
        if maintainable and keys == ["g"]:
            feats.add("maintainable")
        # group by the select ALIASES (k0, k1, ...): arbitrary key
        # expressions (predicates, CASE) are only addressable that way
        q = GenQuery(table=sc.table, select=select, where=where,
                     group_by=[f"k{i}" for i in range(len(keys))],
                     cols=cols, features=frozenset(feats))
        if self._maybe(0.5):
            q.order_by = [f"k{i}" for i in range(len(keys))]
            q.features = q.features | {"ordered_keys"}
        if lite and wlite:
            q.features = q.features | {"sqlite_ok"}
        return q

    def _vector_query(self, sc: Scenario) -> GenQuery:
        dim = len(sc.rows[0][2])
        vec = "[" + ",".join(
            f"{float(x):.3f}" for x in self.rng.normal(0, 1, dim)) + "]"
        k = int(self.rng.integers(2, 9))
        # ORDER BY distance LIMIT k alone — a second sort key would
        # defeat the VectorTopK index rewrite and the pair would diff
        # the brute-force scan against itself (distances over random
        # normals never tie, so the order is deterministic)
        q = GenQuery(
            table=sc.table,
            select=[("id", None)],
            order_by=[f"l2_distance(emb, '{vec}')"],
            limit=k,
            cols=frozenset(["id", "emb"]),
            features=frozenset({"vector", "ordered", "limited"}))
        return q

    def queries(self, scenario: Scenario, n: int) -> List[GenQuery]:
        return [self.query(scenario) for _ in range(n)]

"""moqa metamorphic + differential oracles.

Oracles need no external source of truth — each derives a second
answer the engine must agree with from the engine itself (TLP / NoREC
/ LIMIT-OFFSET algebra, in the SQLancer tradition), or from a stock
sqlite3 database mirroring the same rows where the type surface allows.
Row-sets compare as exact multisets (floats exact too: the engine's
claims for these transformations are bit-identity, not approximation —
only cross-engine sqlite comparisons get a float tolerance).
"""

from __future__ import annotations

import datetime
import decimal
import math
import sqlite3
from typing import List, Optional, Tuple

from tools.moqa.generator import GenQuery, Scenario


# =====================================================================
# row-set comparison
# =====================================================================

#: float significance per comparison mode.  `exact` (12 digits) still
#: tolerates last-ulp differences — a whole-plan XLA program may
#: contract mul-add chains into FMAs that the per-operator path
#: dispatches separately — while anything structural (truncation,
#: wrong branch, dropped rows) blows well past 12 digits.  `tol`
#: (9 digits) additionally absorbs reduction-order noise for pairs
#: whose sum order differs by design.  Ints/decimals/strings/bools
#: compare exactly in both modes (the engine's exactness contract
#: rides int64/decimal, never floats).
_SIG = {"exact": 12, "tol": 9}


def _norm_cell(v, mode: str):
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, decimal.Decimal):
        return ("d", str(decimal.Decimal(v).normalize()))
    if isinstance(v, float):
        if math.isnan(v):
            return ("f", "nan")
        if abs(v) < 1e-9:
            # significant-digit bucketing breaks down at zero: an FMA-
            # contracted fused program returns 1.7e-15 where the
            # per-op path returns exactly 0.0 — same answer, every
            # "significant" digit different.  Snap sub-1e-9 magnitudes
            # to zero on BOTH sides before formatting.
            v = 0.0
        if mode == "xengine" and float(v).is_integer():
            # cross-engine: sqlite's dynamic typing returns ints where
            # the engine's static typing returns floats — compare by
            # value, not host type
            return int(v)
        digits = _SIG.get(mode, 9)
        return ("f", f"{v:.{digits}g}")
    if isinstance(v, (datetime.date, datetime.datetime)):
        return ("t", str(v))
    return v


def normalize_rows(rows: List[tuple], mode: str = "exact"):
    return [tuple(_norm_cell(c, mode) for c in r) for r in rows]


def diff_rows(a: List[tuple], b: List[tuple], ordered: bool,
              tol_floats: bool = False,
              mode: Optional[str] = None) -> Optional[str]:
    """None when equal; otherwise a compact human-readable diff.
    mode: 'exact' | 'tol' | 'xengine' (tol + int/float unification);
    tol_floats=True is shorthand for mode='tol'."""
    if mode is None:
        mode = "tol" if tol_floats else "exact"
    na = normalize_rows(a, mode)
    nb = normalize_rows(b, mode)
    if not ordered:
        na = sorted(na, key=repr)
        nb = sorted(nb, key=repr)
    if na == nb:
        return None
    only_a = [r for r in na if r not in nb]
    only_b = [r for r in nb if r not in na]
    return (f"{len(a)} vs {len(b)} rows; "
            f"only-left {only_a[:3]!r}; only-right {only_b[:3]!r}")


def diff_rows_close(a: List[tuple], b: List[tuple], rel: float = 1e-2,
                    abs_tol: float = 1e-2) -> Optional[str]:
    """Paired-row comparison at an EXPLICIT float tolerance — for
    lockstep pairs whose variant legally changes float precision (the
    narrow-encodings bf16 compute lanes: 8 mantissa bits leave ~0.4%
    relative error per input, far past the sig-digit buckets of
    diff_rows).  Rows pair positionally — callers keep both sides
    deterministically ordered (ORDER BY the group key) — and every
    non-float cell still compares EXACTLY: the int/decimal/string
    exactness contract survives narrowing by design, so a count or
    decimal sum that moves at all is a finding, not noise."""
    if len(a) != len(b):
        return f"{len(a)} vs {len(b)} rows"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if len(ra) != len(rb):
            return f"row {i}: arity {len(ra)} vs {len(rb)}"
        for j, (x, y) in enumerate(zip(ra, rb)):
            if isinstance(x, float) or isinstance(y, float):
                fx, fy = float(x), float(y)
                if math.isnan(fx) and math.isnan(fy):
                    continue
                if not math.isclose(fx, fy, rel_tol=rel,
                                    abs_tol=abs_tol):
                    return (f"row {i} col {j}: {x!r} vs {y!r} beyond "
                            f"rel={rel} abs={abs_tol}")
            elif _norm_cell(x, "exact") != _norm_cell(y, "exact"):
                return (f"row {i} col {j}: {x!r} vs {y!r} "
                        f"(exact-cell contract)")
    return None


# =====================================================================
# metamorphic oracles (engine-only)
# =====================================================================

def tlp_check(execute, q: GenQuery, partition_sql: str
              ) -> Optional[str]:
    """Ternary Logic Partitioning: for a plain SELECT,
    Q == Q[p] ∪ Q[not p] ∪ Q[p is null] as multisets."""
    base = execute(q.sql())
    parts: List[tuple] = []
    for branch in (partition_sql, f"not ({partition_sql})",
                   f"({partition_sql}) is null"):
        qb = q.clone(where=q.where + [branch])
        parts.extend(execute(qb.sql()))
    return diff_rows(base, parts, ordered=False)


def norec_check(execute, table: str, pred_sql: str,
                where: List[str]) -> Optional[str]:
    """NoREC-style cardinality: the optimized COUNT under a predicate
    equals the unoptimizable row-wise sum of the predicate."""
    wh = (" where " + " and ".join(f"({w})" for w in where)) if where \
        else ""
    (n_opt,), = execute(f"select count(*) c from {table}{wh}"
                        + (" and " if where else " where ")
                        + f"({pred_sql})")
    (n_raw,), = execute(
        f"select sum(case when ({pred_sql}) then 1 else 0 end) c "
        f"from {table}{wh}")
    n_raw = n_raw or 0
    if int(n_opt) != int(n_raw):
        return f"count(*) where p = {n_opt} but sum(p as int) = {n_raw}"
    return None


def limit_algebra_check(execute, q: GenQuery) -> Optional[str]:
    """LIMIT/OFFSET algebra over a deterministic total order: the
    limited query must be an exact slice of the unlimited one."""
    full = execute(q.clone(limit=None, offset=None).sql())
    k = q.limit if q.limit is not None else len(full)
    off = q.offset or 0
    want = full[off:off + k]
    got = execute(q.sql())
    return diff_rows(got, want, ordered=True)


# =====================================================================
# sqlite differential oracle
# =====================================================================

def sqlite_setup(scenario: Scenario) -> Optional[sqlite3.Connection]:
    """Mirror the scenario's sqlite-compatible columns into an
    in-memory sqlite database; None when nothing mirrors."""
    cols = [c for c in scenario.columns if c.sqlite_type]
    if not cols:
        return None
    conn = sqlite3.connect(":memory:")
    decl = ", ".join(f"{c.name} {c.sqlite_type}" for c in cols)
    conn.execute(f"create table {scenario.table} ({decl})")
    idx = [i for i, c in enumerate(scenario.columns) if c.sqlite_type]
    data = [tuple(row[i] for i in idx) for row in scenario.rows]
    ph = ",".join("?" * len(cols))
    conn.executemany(
        f"insert into {scenario.table} values ({ph})", data)
    return conn


def sqlite_check(execute, conn: sqlite3.Connection,
                 q: GenQuery) -> Optional[str]:
    """Cross-engine diff against sqlite for the type-compatible query
    subset.  Floats tolerant (reduction order differs by design)."""
    sql = q.sql()
    try:
        want = [tuple(r) for r in conn.execute(sql).fetchall()]
    except sqlite3.Error as e:
        return f"sqlite rejected mirrored query: {e}"
    got = execute(sql)
    ordered = bool(q.order_by)
    return diff_rows(got, want, ordered=ordered, mode="xengine")

"""moqa planted-bug drills — test-only reintroductions of two known
historical bug classes, used to prove the analyzer actually catches
and reduces what it claims to (tests/test_moqa.py, precheck
--qa-smoke).  Mirrors tools/mosan.plant_eviction_race.

  stale-dict-lut   the PR-7 compile-key bug: fragment programs bake
                   dictionary LOOKUP TABLES at trace time; keying the
                   compile cache on dictionary LENGTH instead of
                   CONTENT serves a stale LUT after any same-
                   cardinality string churn — plausible rows, wrong
                   strings.  Caught by the cache-stale pair.

  pad-leak         the padded-tail bug class: an aggregate kernel that
                   sums RAW data instead of masked data reads the
                   padding.  With zero padding the answer is silently
                   right; with the canary armed (utils/qa.py) the
                   poisoned tail turns the leak into a loud NaN /
                   absurd magnitude.  Caught ONLY by the canary pair —
                   the drill that justifies the canary's existence.

Both planters clear the process-global fragment compile cache on entry
AND exit: compiled-under-the-bug programs must not leak into later
(clean) runs, and clean pre-compiled programs must not mask the bug.
They also SWAP IN an isolated findings sink for the key auditor
(utils/keys.py, armed suite-wide under pytest): the auditor rightly
screams about a planted key collision, and those deliberate findings
must not leak into the session-wide zero-mismatch gate
(tests/test_mokey.py::test_suite_runs_key_audit_clean).  Callers that
want the auditor's verdict on a plant open their own nested
keys.capture() inside the plant scope.
"""

from __future__ import annotations

from contextlib import contextmanager


def _clear_fragment_cache():
    from matrixone_tpu.vm import fusion
    fusion.CACHE.clear()


@contextmanager
def plant_stale_dict_lut():
    """Key fragment programs on dictionary LENGTH only (the pre-fix
    PR-7 shape): same-cardinality content churn now serves stale LUTs."""
    from matrixone_tpu.utils import keys
    from matrixone_tpu.vm import fusion

    original = fusion._dict_key

    def length_only_key(d):
        # THE PLANT: content hash dropped from the compile key
        return None if d is None else (len(d),)

    _clear_fragment_cache()
    fusion._dict_key = length_only_key
    try:
        with keys.capture():
            yield
    finally:
        fusion._dict_key = original
        _clear_fragment_cache()


@contextmanager
def plant_pad_leak():
    """Sum kernels read RAW values instead of masked values (the
    padded-tail leak class): correct with zero padding, loudly wrong
    under the armed canary."""
    import jax
    import jax.numpy as jnp
    from matrixone_tpu.ops import agg as A

    orig_seg_sum = A.seg_sum
    orig_scalar_sum = A.scalar_sum

    def leaky_seg_sum(values, gids, mask, max_groups, use_pallas=False):
        # THE PLANT: mask dropped — padding rows contribute their raw
        # buffer contents to whatever group their garbage gid lands in
        return jax.ops.segment_sum(values, gids,
                                   num_segments=max_groups)

    def leaky_scalar_sum(values, mask):
        return jnp.sum(values)

    from matrixone_tpu.utils import keys
    _clear_fragment_cache()
    A.seg_sum = leaky_seg_sum
    A.scalar_sum = leaky_scalar_sum
    try:
        with keys.capture():
            yield
    finally:
        A.seg_sum = orig_seg_sum
        A.scalar_sum = orig_scalar_sum
        _clear_fragment_cache()


_PLANTS = {"stale-dict-lut": plant_stale_dict_lut,
           "pad-leak": plant_pad_leak}


def plant(name: str):
    try:
        return _PLANTS[name]()
    except KeyError:
        raise ValueError(f"unknown plant {name!r}; use "
                         f"{sorted(_PLANTS)}")


def plant_names():
    return sorted(_PLANTS)

"""moqa automatic repro reducer.

A corpus finding names a (schema, data, query, config-pair) quadruple;
this module shrinks it to the minimal quadruple that still fails and
renders it as a ready-to-paste regression test.  Shrinking is plain
delta-debugging against a `still_fails` probe that rebuilds a fresh
in-memory engine per attempt (tools/moqa.replay):

  1. rows:   halves, then quarters, then single-row removal (ddmin);
  2. query:  drop WHERE parts, ORDER BY, LIMIT/OFFSET, then surplus
             select items (group keys survive — dropping one changes
             the shape under test, which is fine IF it still fails);
  3. columns: drop table columns the reduced query no longer reads.

The probe budget is capped (`max_probes`) so a pathological case costs
bounded time; the partially-reduced repro is still valid — reduction
only ever returns quadruples that were re-verified to fail.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from tools.moqa.generator import GenQuery, Scenario


@dataclasses.dataclass
class Case:
    """A reducible failing case.  `pair` names either a config pair
    (tools/moqa/runner.PAIR_ENV) or an oracle (`oracle:tlp` etc.);
    `partition` carries the TLP/NoREC predicate when one applies."""
    scenario: Scenario
    rows: List[tuple]
    query: GenQuery
    pair: str
    partition: Optional[str] = None

    def replay_args(self):
        sc = dataclasses.replace(self.scenario, rows=self.rows)
        return sc, self.query


def reduce_case(case: Case, still_fails: Callable[["Case"], bool],
                max_probes: int = 80) -> Case:
    """Shrink `case` while `still_fails` keeps returning True."""
    budget = [max_probes]

    def probe(c: Case) -> bool:
        if budget[0] <= 0:
            return False
        budget[0] -= 1
        try:
            return still_fails(c)
        except Exception:  # noqa: BLE001 — a probe that errors is not
            # a smaller failing case; keep shrinking elsewhere
            return False

    # ---- 1. rows: ddmin-style chunk removal
    rows = list(case.rows)
    chunk = max(1, len(rows) // 2)
    while chunk >= 1 and budget[0] > 0:
        i, shrunk = 0, False
        while i < len(rows) and budget[0] > 0:
            trial = rows[:i] + rows[i + chunk:]
            if trial and probe(dataclasses.replace(case, rows=trial)):
                rows = trial
                shrunk = True
            else:
                i += chunk
        if not shrunk:
            if chunk == 1:
                break
            chunk = max(1, chunk // 2)
    case = dataclasses.replace(case, rows=rows)

    # ---- 2. query clause dropping, to a fixpoint (candidates are
    # regenerated from the CURRENT query — a later accepted patch must
    # not resurrect a clause an earlier one already dropped)
    q = case.query
    changed = True
    while changed and budget[0] > 0:
        changed = False
        for patch in _query_shrinks(q):
            trial = dataclasses.replace(case, query=patch)
            if probe(trial):
                case = trial
                q = patch
                changed = True
                break

    # ---- 3. drop table columns the query no longer references
    keep = [c for c in case.scenario.columns
            if _col_in_query(c.name, q) or _col_in_pred(c.name, case)]
    if 0 < len(keep) < len(case.scenario.columns):
        idx = [i for i, c in enumerate(case.scenario.columns)
               if c in keep]
        sc2 = dataclasses.replace(
            case.scenario, columns=keep,
            rows=[tuple(r[i] for i in idx) for r in case.rows])
        trial = Case(sc2, sc2.rows, q, case.pair,
                     partition=case.partition)
        if probe(trial):
            case = trial
    return case


def _col_in_query(name: str, q: GenQuery) -> bool:
    import re
    pat = re.compile(rf"\b{re.escape(name)}\b")
    texts = [e for e, _ in q.select] + q.where + q.group_by + q.order_by
    return any(pat.search(t) for t in texts)


def _col_in_pred(name: str, case: "Case") -> bool:
    import re
    if not case.partition:
        return False
    return bool(re.search(rf"\b{re.escape(name)}\b", case.partition))


def _query_shrinks(q: GenQuery):
    """Candidate simplifications, most aggressive first."""
    out = []
    if q.where:
        out.append(q.clone(where=[]))
        for i in range(len(q.where)):
            out.append(q.clone(where=q.where[:i] + q.where[i + 1:]))
    if q.limit is not None or q.offset:
        out.append(q.clone(limit=None, offset=None))
    if q.order_by:
        out.append(q.clone(order_by=[]))
    if len(q.select) > 1 and not q.group_by:
        for i in range(len(q.select)):
            sel = q.select[:i] + q.select[i + 1:]
            out.append(q.clone(select=sel))
    if q.group_by and len(q.select) > len(q.group_by):
        # drop surplus aggregates (keep the keys + one aggregate)
        nkeys = len(q.group_by)
        for i in range(nkeys, len(q.select)):
            if len(q.select) - 1 > nkeys - 1:
                sel = q.select[:i] + q.select[i + 1:]
                out.append(q.clone(select=sel))
    return out


# =====================================================================
# rendering
# =====================================================================

def render_repro(case: Case, kind: str, seed) -> str:
    """A ready-to-paste pytest regression test for the reduced case."""
    sc, q = case.replay_args()
    rows_sql = ",".join(sc.render_row(r) for r in case.rows)
    name = f"test_moqa_repro_{kind.replace('-', '_')}_{seed}"
    extra = []
    if (q.has("udf") or q.has("join")) and sc.setup_sql:
        extra.append(f"        setup={tuple(sc.setup_sql)!r},")
    if case.partition:
        extra.append(f"        partition={case.partition!r},")
    if q.has("ordered"):
        extra.append("        ordered=True,")
    lines = [
        f"def {name}():",
        f"    # reduced by tools/moqa (seed={seed}, pair="
        f"{case.pair}, kind={kind})",
        f"    from tools import moqa",
        f"    assert moqa.replay(",
        f"        create={sc.create_sql()!r},",
        f"        insert="
        f"{'insert into ' + sc.table + ' values ' + rows_sql!r},",
        f"        query={q.sql()!r},",
        *extra,
        f"        pair={case.pair!r}) == []",
    ]
    return "\n".join(lines)


# =====================================================================
# glue: reduce a runner Finding
# =====================================================================

#: finding kind -> replay mode; kinds not here are not reducible
#: (canary audits attach to a pair run, error kinds carry no diff)
_KIND_MODE = {
    "lockstep-mismatch": "pair",
    "cache-staleness": "pair",
    "canary-in-result": "pair",
    "canary-in-carry": "pair",
    "oracle-tlp": "oracle:tlp",
    "oracle-norec": "oracle:norec",
    "oracle-limit": "oracle:limit",
    "oracle-sqlite": "oracle:sqlite",
}


def reduce_finding(finding, gen) -> str:
    """Rebuild the failing case from a runner Finding and shrink it.
    The probe replays the single (query, pair-or-oracle) through
    tools/moqa.replay on a fresh engine each attempt."""
    from tools import moqa
    from tools.moqa import runner as R
    from tools.moqa.generator import Generator

    mode = _KIND_MODE.get(finding.kind)
    if mode is None or finding.query is None:
        raise ValueError(f"finding kind {finding.kind!r} is not "
                         f"reducible")
    # regenerate the scenario deterministically from the seed
    scenarios = {s.name: s for s in Generator(finding.seed).scenarios()}
    sc = scenarios.get(finding.scenario)
    if sc is None:
        raise ValueError("finding does not name a known scenario")
    pair = finding.pair if mode == "pair" else mode
    if mode == "pair" and pair.startswith("mview"):
        pair = "mview"
    if mode == "pair" and pair not in R.PAIR_ENV:
        pair = "fusion"
    if mode == "oracle:sqlite":
        # the runner's sqlite mirror only ever holds the mirrorable
        # column subset (oracles.sqlite_setup filters), but replay's
        # mirror takes the whole CREATE — pre-drop the unmirrorable
        # columns so the very first probe doesn't die on a decimal/
        # bool/date column the query never reads
        keep = [c for c in sc.columns if c.sqlite_type]
        if 0 < len(keep) < len(sc.columns):
            idx = [i for i, c in enumerate(sc.columns) if c.sqlite_type]
            sc = dataclasses.replace(
                sc, columns=keep,
                rows=[tuple(r[i] for i in idx) for r in sc.rows])

    def still_fails(c: Case) -> bool:
        sc2, q2 = c.replay_args()
        rows_sql = ",".join(sc2.render_row(r) for r in c.rows)
        out = moqa.replay(
            create=sc2.create_sql(),
            insert=f"insert into {sc2.table} values {rows_sql}",
            query=q2.sql(), pair=c.pair,
            setup=tuple(sc2.setup_sql),
            ordered=q2.has("ordered"),
            partition=c.partition)
        return bool(out)

    case = Case(sc, list(sc.rows), finding.query, pair,
                partition=finding.partition)
    if not still_fails(case):
        raise ValueError("case does not reproduce in isolation")
    case = reduce_case(case, still_fails)
    return render_repro(case, finding.kind, finding.seed)

"""moqa config-lattice lockstep runner.

One invariant, many configurations: every execution configuration of
this engine must return the SAME answer.  The runner executes each
generated query under a BASELINE configuration (per-operator path,
serving caches off) and then under paired variant configurations, and
diffs the row-sets exactly:

  fusion          MO_PLAN_FUSION=1 + MO_FUSION_MIN_ROWS=0 (traced
                  whole-plan programs) vs the per-operator path
  dense-groups    MO_DENSE_GROUPS=0 (general hash group path) vs the
                  mixed-radix dense path (floats tolerant: reduction
                  order is config-dependent here by design)
  plan-cache      warm plan-cache hit vs cold compile
  result-cache    warm result-cache hit vs recompute
  udf-tier        MO_UDF_JIT=0 row loop vs jit tier
  canary          padding canary armed (utils/qa.py poisons padded
                  tails) vs disarmed — plus the canary audits; the
                  armed run also forces MO_HAND_KERNELS=1 and
                  MO_NARROW_ENCODINGS=1 so the poisoned tails sweep
                  the Pallas sorted-search/group-scatter kernels and
                  the narrow dict-code path, not just the XLA ops
  narrow-encodings  MO_NARROW_ENCODINGS=1 fused path (int8/int16 dict
                  codes, bf16 float lanes) vs the wide baseline, swept
                  over GROUPED queries (the only shape where the
                  policy engages); the corpus carries no FLOAT32
                  column (doubles stay f64, decimals/counts stay
                  scaled int64) so this pair is EXACT — the bf16
                  tolerance contract is proven by the dedicated f32
                  drill (_run_narrow_f32_drill)
  mview           insert-then-query ≡ query-over-materialized-view,
                  incremental maintenance AND full refresh
  shards          SET ivf_shards=2 cluster-sharded vector search vs
                  local (virtual device mesh permitting)
  cache-stale     warm fusion/plan/result caches, mutate the table,
                  re-run: cached artifacts must never outlive the data

Oracles (tools/moqa/oracles.py) run against the baseline session.
Findings are reduced (tools/moqa/reducer.py) to minimal repros.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from tools.moqa.generator import GenQuery, Generator, Scenario
from tools.moqa import oracles as ORC

# ---------------------------------------------------------------- env

#: the baseline lattice point: per-operator execution, default group
#: path, jit UDF tier, no fusion
ENV_BASELINE = {"MO_PLAN_FUSION": "0", "MO_DENSE_GROUPS": None,
                "MO_FUSION_MIN_ROWS": None, "MO_UDF_JIT": None,
                "MO_NARROW_ENCODINGS": None, "MO_HAND_KERNELS": None}

#: per-pair env overrides (applied on top of the baseline)
PAIR_ENV = {
    "fusion": {"MO_PLAN_FUSION": "1", "MO_FUSION_MIN_ROWS": "0"},
    "dense-groups": {"MO_DENSE_GROUPS": "0"},
    "plan-cache": {},
    "result-cache": {},
    "udf-tier": {"MO_UDF_JIT": "0"},
    # the armed replay also routes through the hand kernels (interpret
    # mode off-TPU) and the narrow dict codes: the padding canary is
    # exactly the instrument that catches a Pallas tile reading its
    # padded tail
    "canary": {"MO_PLAN_FUSION": "1", "MO_FUSION_MIN_ROWS": "0",
               "MO_HAND_KERNELS": "1", "MO_NARROW_ENCODINGS": "1"},
    "narrow-encodings": {"MO_NARROW_ENCODINGS": "1",
                         "MO_PLAN_FUSION": "1",
                         "MO_FUSION_MIN_ROWS": "0"},
    "mview": {},
    "shards": {},
    # device-shard SQL executor (parallel/dist_query.py): the variant
    # SETs query_shards live, same mechanism as the ivf "shards" pair
    "query-shards": {},
    "cache-stale": {"MO_PLAN_FUSION": "1", "MO_FUSION_MIN_ROWS": "0"},
}

#: pairs whose two sides are bit-identical by construction; the rest
#: compare floats at 9 significant digits (reduction order differs:
#: the general hash group path and incremental mview delta maintenance
#: both sum floats in a different order than the baseline recompute —
#: decimal/int sums stay exact everywhere)
EXACT_PAIRS = frozenset({"fusion", "plan-cache", "result-cache",
                         "udf-tier", "canary", "shards",
                         "cache-stale", "narrow-encodings"})

PAIR_NAMES = tuple(PAIR_ENV)


@contextmanager
def env_scope(overrides: Dict[str, Optional[str]]):
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _pair_scope(pair: str):
    env = dict(ENV_BASELINE)
    env.update(PAIR_ENV[pair])
    return env_scope(env)


# ------------------------------------------------------------ findings

class Finding:
    """One corpus finding: a configuration or oracle disagreement.
    `query` keeps the structured GenQuery (when the finding came from
    one) so the reducer can shrink clauses instead of parsing SQL;
    `partition` keeps the TLP/NoREC partition predicate."""

    __slots__ = ("kind", "scenario", "seed", "pair", "sql", "detail",
                 "repro", "query", "partition")

    def __init__(self, kind, scenario, seed, pair, sql, detail,
                 repro=None, query=None, partition=None):
        self.kind = kind
        self.scenario = scenario
        self.seed = seed
        self.pair = pair
        self.sql = sql
        self.detail = detail
        self.repro = repro
        self.query = query
        self.partition = partition

    def format(self) -> str:
        return (f"[{self.kind}] seed={self.seed} scenario="
                f"{self.scenario} pair={self.pair}\n  query: {self.sql}"
                f"\n  {self.detail}")

    def as_dict(self) -> dict:
        return {"kind": self.kind, "scenario": self.scenario,
                "seed": self.seed, "pair": self.pair, "sql": self.sql,
                "detail": self.detail, "repro": self.repro}


# ------------------------------------------------------- live scenario

class LiveScenario:
    """A scenario instantiated on a fresh in-memory engine."""

    def __init__(self, scenario: Scenario, waves: int = 2,
                 serving_off: bool = True):
        from matrixone_tpu.frontend import Session
        from matrixone_tpu.storage.engine import Engine
        self.scenario = scenario
        self.eng = Engine()
        self.sess = Session(catalog=self.eng)
        self.sess.execute(scenario.create_sql())
        rows = (scenario.rows if waves >= 2
                else scenario.rows[:scenario.wave_split])
        if rows:
            self.sess.execute(scenario.insert_sql(rows))
        for ddl in scenario.setup_sql:
            self.sess.execute(ddl)
        if serving_off:
            self.ctl("serving", "plan:off")
    # (result cache is off by default: MO_RESULT_CACHE_MB=0)

    def ctl(self, cmd: str, arg: str) -> str:
        r = self.sess.execute(f"select mo_ctl('{cmd}', '{arg}')")
        return r.rows()[0][0]

    def insert_wave2(self):
        sc = self.scenario
        rest = sc.rows[sc.wave_split:]
        if rest:
            self.sess.execute(sc.insert_sql(rest))

    def rows(self, sql: str) -> List[tuple]:
        return self.sess.execute(sql).rows()

    def close(self):
        self.sess.close()


def _ordered(q: GenQuery) -> bool:
    return q.has("ordered")


def _applicable(pair: str, q: GenQuery) -> bool:
    if pair in ("fusion", "plan-cache", "result-cache", "canary",
                "cache-stale"):
        return not q.has("vector")
    if pair == "narrow-encodings":
        # the policy only bites on fused agg lanes / dict codes — a
        # grouped-only sweep covers every engaged code path at a
        # fraction of the lockstep cost (the f32 drill below carries
        # the precision teeth)
        return q.has("grouped")
    if pair == "dense-groups":
        return q.has("grouped")
    if pair == "udf-tier":
        return q.has("udf")
    if pair == "mview":
        return q.has("maintainable")
    if pair == "shards":
        return q.has("vector")
    if pair == "query-shards":
        # every non-vector family: the executor itself degrades to the
        # local plan when the shape doesn't shard, and THAT ladder is
        # exactly what the lockstep pair must exercise
        return not q.has("vector")
    return False


def _mesh_ok(n: int = 2) -> bool:
    import jax
    try:
        return len(jax.devices()) >= n
    except RuntimeError:
        return False


# =====================================================================
# the corpus run
# =====================================================================

def run_corpus(seed: int = 0, queries_per_scenario: int = 80,
               pairs: Optional[List[str]] = None,
               time_budget_s: Optional[float] = None,
               reduce_findings: int = 4,
               oracle_fraction: float = 0.34,
               stale_fraction: float = 0.2,
               max_views: int = 10) -> dict:
    """Run the full differential corpus for one seed.  Returns a report
    dict (see `format_report`); report['findings'] empty == the
    invariant held everywhere the corpus looked."""
    from matrixone_tpu.utils import qa

    t0 = time.monotonic()
    gen = Generator(seed)
    scenarios = gen.scenarios()
    pairs = list(PAIR_NAMES) if pairs is None else list(pairs)
    if "shards" in pairs and not _mesh_ok():
        pairs.remove("shards")
    if "query-shards" in pairs and not _mesh_ok():
        pairs.remove("query-shards")
    findings: List[Finding] = []
    checks: Dict[str, int] = {}
    pair_counts: Dict[str, int] = {p: 0 for p in pairs}
    n_queries = 0
    deadline = (t0 + time_budget_s) if time_budget_s else None

    def note(oracle: str):
        checks[oracle] = checks.get(oracle, 0) + 1
        qa.note_check(oracle)

    def found(kind, scenario, pair, sql, detail, q=None,
              partition=None):
        findings.append(Finding(kind, scenario, seed, pair, sql,
                                detail, query=q, partition=partition))
        if not kind.startswith("canary-"):
            # canary events already drove mo_qa_findings_total at the
            # audit point (qa.record_finding) — don't double-count
            qa.note_finding(kind)

    for sc in scenarios:
        if deadline and time.monotonic() > deadline:
            break
        n_q = queries_per_scenario if "vector" not in sc.features \
            else max(8, queries_per_scenario // 5)
        if "join_scenario" in sc.features:
            # the join/window scenario rides every non-vector pair too;
            # half the per-scenario budget keeps the tier-1 gate bounded
            n_q = max(12, n_q // 2)
        qs = gen.queries(sc, n_q)
        n_queries += len(qs)
        qa.note_query(len(qs))

        live = LiveScenario(sc)
        base_rows: Dict[int, List[tuple]] = {}
        base_err: Dict[int, str] = {}
        try:
            with env_scope(ENV_BASELINE):
                for i, q in enumerate(qs):
                    try:
                        base_rows[i] = live.rows(q.sql())
                    except Exception as e:  # noqa: BLE001 — a baseline
                        # rejection is itself a corpus finding (dialect
                        # drift between generator and engine)
                        base_err[i] = repr(e)
                        found("gen-error", sc.name, "baseline",
                              q.sql(), repr(e))
                # ---- metamorphic oracles on the baseline session
                _run_oracles(live, sc, qs, base_rows, base_err, gen,
                             oracle_fraction, note, found)

            # ---- same-session env pairs
            for pair in ("fusion", "dense-groups", "udf-tier",
                         "narrow-encodings", "shards", "query-shards"):
                if pair not in pairs:
                    continue
                if pair == "shards":
                    # sharding is a SESSION variable, not env: the
                    # session snapshots MO_IVF_SHARDS at creation, so
                    # the variant must SET it live (and restore)
                    live.sess.execute("set ivf_shards = 2")
                if pair == "query-shards":
                    # same mechanism for the SQL device-shard executor;
                    # dist_min_rows drops so the tiny corpus tables
                    # actually shard (restored below)
                    live.sess.execute("set query_shards = 2")
                    live.sess.execute("set dist_min_rows = 0")
                try:
                    with _pair_scope(pair):
                        taken = 0
                        for i, q in enumerate(qs):
                            if i in base_err \
                                    or not _applicable(pair, q):
                                continue
                            if pair == "narrow-encodings":
                                # half-stride sample: the pair is a
                                # config sweep over one policy flip —
                                # every other grouped query keeps every
                                # engaged shape in the gate's budget
                                # (the f32 drill carries the teeth)
                                taken += 1
                                if taken % 2 == 0:
                                    continue
                            _diff_one(live, q, base_rows[i], pair, sc,
                                      note, found, pair_counts)
                finally:
                    if pair == "shards":
                        live.sess.execute("set ivf_shards = 0")
                    if pair == "query-shards":
                        live.sess.execute("set query_shards = 0")
                        live.sess.execute("set dist_min_rows = 100000")

            # ---- warm-cache pairs (same session, caches on)
            if "plan-cache" in pairs:
                live.ctl("serving", "plan:on")
                with _pair_scope("plan-cache"):
                    for i, q in enumerate(qs):
                        if i in base_err or not _applicable(
                                "plan-cache", q):
                            continue
                        _diff_one(live, q, base_rows[i], "plan-cache",
                                  sc, note, found, pair_counts,
                                  runs=2)
                live.ctl("serving", "plan:off")
            if "result-cache" in pairs:
                live.ctl("serving", "result:on")
                with _pair_scope("result-cache"):
                    for i, q in enumerate(qs):
                        if i in base_err or not _applicable(
                                "result-cache", q):
                            continue
                        _diff_one(live, q, base_rows[i],
                                  "result-cache", sc, note, found,
                                  pair_counts, runs=2)
                live.ctl("serving", "result:off")
                live.ctl("serving", "clear")
        finally:
            live.close()

        # ---- pairs needing their own engine
        if "canary" in pairs and "vector" not in sc.features:
            _run_canary_pair(sc, qs, base_rows, base_err, note, found,
                             pair_counts)
        if "mview" in pairs and "vector" not in sc.features:
            _run_mview_pair(sc, qs, base_rows, base_err, note, found,
                            pair_counts, max_views)
        if "cache-stale" in pairs and "vector" not in sc.features:
            _run_stale_pair(sc, qs, base_err, note, found, pair_counts,
                            stale_fraction)

    # ---- narrow-encodings f32 drill (own tables: the corpus carries
    # no FLOAT32 column, so the bf16 compute-lane tolerance needs its
    # own deliberately bf16-inexact data)
    if "narrow-encodings" in pairs:
        _run_narrow_f32_drill(seed, note, found, pair_counts)

    # ---- reduce the first few findings to minimal repros
    reduced = 0
    if reduce_findings:
        from tools.moqa import reducer
        for f in findings:
            if reduced >= reduce_findings:
                break
            if f.kind in ("gen-error",):
                continue
            try:
                f.repro = reducer.reduce_finding(f, gen)
                reduced += 1
            except Exception as e:  # noqa: BLE001 — reduction is best-
                # effort; the un-reduced finding still fails the gate
                f.repro = f"<reduction failed: {e!r}>"

    report = {
        "seed": seed,
        "queries": n_queries,
        "scenarios": [sc.name for sc in scenarios],
        "pairs": {p: pair_counts.get(p, 0) for p in pairs},
        "oracle_checks": checks,
        "total_checks": sum(checks.values()),
        "findings": [f.as_dict() for f in findings],
        "findings_formatted": [f.format() for f in findings],
        "seconds": round(time.monotonic() - t0, 2),
    }
    _remember(report)
    return report


def _diff_one(live: LiveScenario, q: GenQuery, base: List[tuple],
              pair: str, sc: Scenario, note, found, pair_counts,
              runs: int = 1):
    tol = pair not in EXACT_PAIRS
    try:
        got = None
        for _ in range(runs):
            got = live.rows(q.sql())
    except Exception as e:  # noqa: BLE001 — an error on one side of a
        # lockstep pair IS the finding
        found("error-divergence", sc.name, pair, q.sql(),
              f"variant raised {e!r} but baseline succeeded", q=q)
        return
    note("lockstep")
    pair_counts[pair] = pair_counts.get(pair, 0) + 1
    d = ORC.diff_rows(base, got, ordered=_ordered(q),
                      tol_floats=tol)
    if d is not None:
        found("lockstep-mismatch", sc.name, pair, q.sql(), d, q=q)


def _run_oracles(live, sc, qs, base_rows, base_err, gen,
                 fraction, note, found):
    if fraction <= 0 or "vector" in sc.features:
        return
    conn = ORC.sqlite_setup(sc)
    try:
        for i, q in enumerate(qs):
            if i in base_err:
                continue
            # deterministic thinning: every k-th query gets the oracles
            if fraction < 1.0 and (i % max(1, round(1 / fraction))):
                continue
            ex = live.rows
            if q.has("tlp_ok"):
                p = gen.partition_pred()
                d = ORC.tlp_check(ex, q, p.sql)
                note("tlp")
                if d is not None:
                    found("oracle-tlp", sc.name, f"p={p.sql}", q.sql(),
                          d, q=q, partition=p.sql)
                d = ORC.norec_check(ex, sc.table, p.sql, q.where)
                note("norec")
                if d is not None:
                    found("oracle-norec", sc.name, f"p={p.sql}",
                          q.sql(), d, q=q, partition=p.sql)
            if q.has("limited") and q.has("ordered"):
                d = ORC.limit_algebra_check(ex, q)
                note("limit")
                if d is not None:
                    found("oracle-limit", sc.name, "-", q.sql(), d,
                          q=q)
            if conn is not None and q.has("sqlite_ok") \
                    and not q.has("limited"):
                d = ORC.sqlite_check(ex, conn, q)
                note("sqlite")
                if d is not None:
                    found("oracle-sqlite", sc.name, "-", q.sql(), d,
                          q=q)
    finally:
        if conn is not None:
            conn.close()


def _run_canary_pair(sc, qs, base_rows, base_err, note, found,
                     pair_counts):
    """Replay the scenario with the padding canary armed: poisoned
    tails must change nothing, and the result/carry audits must stay
    silent."""
    from matrixone_tpu.utils import qa
    with qa.armed_scope(), qa.capture() as probe, \
            _pair_scope("canary"):
        live = LiveScenario(sc)
        try:
            for i, q in enumerate(qs):
                if i in base_err or not _applicable("canary", q):
                    continue
                try:
                    got = live.rows(q.sql())
                except Exception as e:  # noqa: BLE001 — lockstep error
                    # divergence (see _diff_one)
                    found("error-divergence", sc.name, "canary",
                          q.sql(), f"armed run raised {e!r}")
                    continue
                note("lockstep")
                pair_counts["canary"] = pair_counts.get("canary", 0) + 1
                d = ORC.diff_rows(base_rows[i], got, ordered=_ordered(q))
                if d is not None:
                    found("lockstep-mismatch", sc.name, "canary",
                          q.sql(), d, q=q)
        finally:
            live.close()
    for f in probe.findings():
        found(f.rule, sc.name, "canary", "-", f.format())


def _run_narrow_f32_drill(seed, note, found, pair_counts):
    """The documented-tolerance half of the narrow-encodings contract.

    The corpus scenarios carry no FLOAT32 column (doubles stay f64,
    decimals/counts stay scaled int64), so the lattice pair proves
    narrowing is LOSSLESS where the engine promises exactness — but
    never exercises the bf16 compute lane.  This drill builds a small
    f32 table whose values are deliberately bf16-INEXACT (mantissas
    longer than 8 bits), runs grouped float aggregates wide vs
    narrowed under the fused path, and holds the variant to the
    documented tolerance: group keys, counts and decimal sums compare
    EXACT; f32 sums/avgs/extrema within bf16 relative error (8
    mantissa bits -> ~0.4% per input; the drill's same-sign values
    keep sums from cancelling the error estimate away)."""
    import random

    rnd = random.Random(seed * 7919 + 13)
    vals = []
    for i in range(512):
        g = f"g{i % 7}"
        f = rnd.uniform(0.5, 2.0) + 1e-3 * rnd.random()
        q = rnd.randrange(0, 9999) / 100.0
        vals.append(f"({i}, '{g}', {f!r}, {q:.2f})")
    ddl = ("create table qa_nf (k bigint, g varchar(4), f float, "
           "q decimal(12,2))")
    ins = "insert into qa_nf values " + ", ".join(vals)
    sqls = (
        "select g, count(*) c, sum(q) sq, sum(f) sf, avg(f) af "
        "from qa_nf group by g order by g",
        "select g, sum(f) sf, min(f) mn, max(f) mx from qa_nf "
        "where k < 341 group by g order by g",
    )

    def run(narrow: bool):
        from matrixone_tpu.frontend import Session
        from matrixone_tpu.storage.engine import Engine
        env = dict(ENV_BASELINE)
        env.update({"MO_PLAN_FUSION": "1", "MO_FUSION_MIN_ROWS": "0"})
        if narrow:
            env["MO_NARROW_ENCODINGS"] = "1"
        out = []
        with env_scope(env):
            sess = Session(catalog=Engine())
            try:
                sess.execute(ddl)
                sess.execute(ins)
                for s in sqls:
                    out.append(sess.execute(s).rows())
            finally:
                sess.close()
        return out

    try:
        wide = run(False)
        slim = run(True)
    except Exception as e:  # noqa: BLE001 — an error on one side of a
        # lockstep pair IS the finding
        found("error-divergence", "narrow-f32", "narrow-encodings",
              "qa_nf drill", f"drill raised {e!r}")
        return
    for s, a, b in zip(sqls, wide, slim):
        note("narrow-f32")
        pair_counts["narrow-encodings"] = \
            pair_counts.get("narrow-encodings", 0) + 1
        d = ORC.diff_rows_close(a, b, rel=1e-2, abs_tol=1e-2)
        if d is not None:
            found("lockstep-mismatch", "narrow-f32",
                  "narrow-encodings", s, d)


def _run_mview_pair(sc, qs, base_rows, base_err, note, found,
                    pair_counts, max_views):
    """Commutation: insert-then-query ≡ query-over-materialized-view,
    under incremental maintenance and again after a full refresh."""
    cand = [(i, q) for i, q in enumerate(qs)
            if i not in base_err and _applicable("mview", q)]
    if not cand:
        return
    cand = cand[:max_views]
    with env_scope(ENV_BASELINE):
        live = LiveScenario(sc, waves=1)
        try:
            views = {}
            for i, q in cand:
                name = f"qa_mv_{i}"
                body = q.clone(order_by=[], limit=None, offset=None)
                try:
                    live.sess.execute(
                        f"create materialized view {name} as "
                        f"{body.sql()}")
                    views[i] = name
                except Exception as e:  # noqa: BLE001 — a shape the
                    # mview planner rejects is simply not applicable
                    continue
            live.insert_wave2()
            from matrixone_tpu.mview import catalog as vcat
            reg = vcat.registry_for(live.eng)
            for i, q in cand:
                if i not in views:
                    continue
                mode = reg[views[i]].mode if views[i] in reg else "full"
                for phase in ("incremental", "full"):
                    if phase == "incremental" and mode != "incremental":
                        # a full-mode view refreshes ON DEMAND by
                        # design (SHOW/EXPLAIN mark it); the
                        # insert-then-query commutation only binds
                        # after the refresh below
                        continue
                    if phase == "full":
                        live.ctl("mview", f"refresh:{views[i]}")
                    try:
                        got = live.rows(f"select * from {views[i]}")
                    except Exception as e:  # noqa: BLE001 — lockstep
                        # error divergence
                        found("error-divergence", sc.name, "mview",
                              q.sql(), f"{phase} read raised {e!r}")
                        break
                    note("mview")
                    pair_counts["mview"] = pair_counts.get(
                        "mview", 0) + 1
                    d = ORC.diff_rows(base_rows[i], got,
                                      ordered=False, tol_floats=True)
                    if d is not None:
                        found("lockstep-mismatch", sc.name,
                              f"mview-{phase}", q.sql(), d, q=q)
        finally:
            live.close()


def _run_stale_pair(sc, qs, base_err, note, found, pair_counts,
                    fraction):
    """Warm every cache layer, mutate the table, re-run: a cached plan,
    result, or compiled fragment that outlives the data it was built
    from returns plausible-but-wrong rows — exactly the PR-7 stale-LUT
    bug class.  Both phases run with the capture auditor ARMED
    (MO_KEY_AUDIT semantics, utils/keys.py): every rotate-rebuild
    lockstep also re-hashes the dictionary/constant content behind
    every fragment/plan-tree cache hit, so a weakened compile key
    surfaces as a `key-capture-mismatch` finding with both stacks even
    when the row diff happens to pass."""
    from matrixone_tpu.utils import keys as keyaudit
    cand = [(i, q) for i, q in enumerate(qs)
            if i not in base_err and _applicable("cache-stale", q)]
    step = max(1, round(1 / max(fraction, 1e-6)))
    cand = cand[::step]
    if not cand:
        return
    with _pair_scope("cache-stale"), keyaudit.armed_scope(), \
            keyaudit.capture() as kcap:
        live = LiveScenario(sc, waves=1, serving_off=False)
        try:
            live.ctl("serving", "result:on")
            for i, q in cand:        # warm: compile + fill caches
                try:
                    live.rows(q.sql())
                except Exception:  # noqa: BLE001 — baseline-rejected
                    # shapes were already reported; wave-1 data can
                    # also legitimately reject (e.g. empty vector set)
                    continue
            # the mutation: new rows AND string-content churn that
            # keeps dictionary LENGTHS stable (the stale-LUT trap)
            live.insert_wave2()
            mut = [m for m in (
                f"update {sc.table} set g = 'zq' where g = 'aa'",
                f"update {sc.table} set s = 'zz99' where s = 's00'",
            ) if any(c.name in ("g", "s") for c in sc.columns)]
            for m in mut:
                live.sess.execute(m)
            # truth: same engine, cold serving caches, unfused path
            live.ctl("serving", "clear")
            live.ctl("serving", "plan:off")
            live.ctl("serving", "result:off")
            with env_scope(ENV_BASELINE):
                truth = {}
                for i, q in cand:
                    try:
                        truth[i] = live.rows(q.sql())
                    except Exception:  # noqa: BLE001 — see warm loop
                        continue
            # warm re-run: caches + compiled fragments from BEFORE the
            # mutation must have been invalidated/re-keyed
            live.ctl("serving", "plan:on")
            live.ctl("serving", "result:on")
            for i, q in cand:
                if i not in truth:
                    continue
                try:
                    got = live.rows(q.sql())
                except Exception as e:  # noqa: BLE001 — lockstep error
                    # divergence
                    found("error-divergence", sc.name, "cache-stale",
                          q.sql(), f"post-mutation run raised {e!r}")
                    continue
                note("staleness")
                pair_counts["cache-stale"] = pair_counts.get(
                    "cache-stale", 0) + 1
                d = ORC.diff_rows(truth[i], got, ordered=_ordered(q))
                if d is not None:
                    found("cache-staleness", sc.name, "cache-stale",
                          q.sql(), d, q=q)
            # ---- phase 2: shape-preserving rebuild.  Same table,
            # same row COUNT and dictionary SIZES as the warm phase,
            # rotated string CONTENT: any compiled artifact keyed on
            # anything weaker than content (the PR-7 stale-LUT class)
            # now serves stale rows while every shape-based key
            # collides on purpose.
            from tools import moqa as _moqa
            wave1 = sc.rows[:sc.wave_split]
            live.sess.execute(f"drop table {sc.table}")
            live.sess.execute(sc.create_sql())
            if wave1:
                live.sess.execute(_moqa.rotate_insert_strings(
                    sc.insert_sql(wave1)))
            with env_scope(ENV_BASELINE):
                live.ctl("serving", "clear")
                live.ctl("serving", "plan:off")
                live.ctl("serving", "result:off")
                truth2 = {}
                for i, q in cand:
                    try:
                        truth2[i] = live.rows(q.sql())
                    except Exception:  # noqa: BLE001 — see warm loop
                        continue
                live.ctl("serving", "plan:on")
            for i, q in cand:
                if i not in truth2:
                    continue
                try:
                    got = live.rows(q.sql())
                except Exception as e:  # noqa: BLE001 — lockstep error
                    # divergence
                    found("error-divergence", sc.name, "cache-stale",
                          q.sql(), f"post-rebuild run raised {e!r}")
                    continue
                note("staleness")
                pair_counts["cache-stale"] = pair_counts.get(
                    "cache-stale", 0) + 1
                d = ORC.diff_rows(truth2[i], got, ordered=_ordered(q))
                if d is not None:
                    found("cache-staleness", sc.name, "cache-stale",
                          q.sql(), d, q=q)
            # ---- the capture auditor's verdict on both phases: a
            # mismatch here is a compile key that COLLIDED across the
            # mutation/rebuild — report it even when the row diff
            # passed (a zero-row or value-coincident query can mask
            # the stale program)
            for kf in kcap.findings():
                note("staleness")
                found("key-capture-mismatch", sc.name, "cache-stale",
                      f"{kf.site} capture {kf.name!r}", kf.detail)
        finally:
            live.close()


# ----------------------------------------------------------- last run

_LAST_RUN: Optional[dict] = None


def _remember(report: dict):
    global _LAST_RUN
    slim = dict(report)
    slim["findings_formatted"] = slim["findings_formatted"][:10]
    slim["findings"] = slim["findings"][:10]
    slim["ts"] = time.time()
    _LAST_RUN = slim


def last_run() -> Optional[dict]:
    return _LAST_RUN

"""mosan driver — directed concurrency stress drill + ops CLI for the
runtime sanitizer in `matrixone_tpu/utils/san.py`.

The drill spins N writer threads against M cached-reader threads over
one engine with the serving layer armed (result cache ON, admission
slots bounded) while the sanitizer watches: lock-order edges, blocking-
under-lock choke points, guarded-structure mutations and thread leaks
all exercise their real schedules.  A clean run returns zero findings;
`plant="eviction-race"` re-introduces the PR-4 result-cache eviction
race (stale-path pop outside the cache lock) and the drill must catch
it — the regression proof tests/test_mosan.py pins.

Used by:
  * `python -m tools.mosan --stress [secs]` (ops / debugging)
  * `python -m tools.precheck --san-smoke` (CI smoke, <30s)
  * tests/test_mosan.py (tier-1 gate + planted-race drill)
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional


def stress_seconds(default: float = 2.0) -> float:
    """MO_SAN_STRESS_SECS knob (README "Concurrency sanitizer")."""
    try:
        return float(os.environ.get("MO_SAN_STRESS_SECS", "") or default)
    except ValueError:
        return default


@contextmanager
def plant_eviction_race():
    """Re-introduce the PR-4 ResultCache eviction race: the stale-path
    pop runs OUTSIDE the cache lock (a concurrent put() can interleave,
    evicting the fresh entry and corrupting the byte budget).  The
    mutation still rides the auditor hook (`san.mutating`) — the
    discipline the write auditor enforces is exactly that the hook and
    the mutation stay inside the owning lock's critical section."""
    from matrixone_tpu.serving.result_cache import ResultCache
    from matrixone_tpu.utils import san

    original = ResultCache.get

    def racy_get(self, key, current_versions):
        from matrixone_tpu.utils import metrics as M
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
        if e is None:
            M.result_cache_ops.inc(outcome="miss")
            return None
        now = current_versions(e.versions)
        if now != e.versions:
            # THE PLANT: pre-fix PR-4 code shape — evict the stale entry
            # after releasing the lock, no identity check
            san.mutating(self)
            self._entries.pop(key, None)
            self._bytes -= e.nbytes
            M.result_cache_ops.inc(outcome="stale")
            return None
        M.result_cache_ops.inc(outcome="hit")
        return e.batch, e.versions

    ResultCache.get = racy_get
    try:
        yield
    finally:
        ResultCache.get = original


def run_stress(seconds: Optional[float] = None, writers: int = 2,
               readers: int = 3, plant: Optional[str] = None) -> dict:
    """N writer / M cached-reader threads over engine + serving caches +
    admission with the sanitizer armed in an isolated sink.  Returns a
    report dict; `findings` empty == clean."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.serving import serving_for
    from matrixone_tpu.storage.engine import Engine
    from matrixone_tpu.utils import san

    seconds = stress_seconds() if seconds is None else float(seconds)
    if plant not in (None, "eviction-race"):
        raise ValueError(f"unknown plant {plant!r}; use 'eviction-race'")

    eng = Engine()
    s = Session(catalog=eng)
    s.execute("create table san_ctr (id bigint primary key, v bigint)")
    s.execute("insert into san_ctr values "
              + ", ".join(f"({i}, 0)" for i in range(1, writers + 1)))
    s.execute("select mo_ctl('serving','result:on')")
    sv = serving_for(eng)
    sv.admission.slots = max(2, readers)       # bounded, really queueing
    s.execute("select sum(v) from san_ctr")    # warm compile

    stop = threading.Event()
    errors: list = []
    counts = {"reads": 0, "writes": 0}

    def writer(row: int):
        sw = Session(catalog=eng)
        try:
            while not stop.is_set():
                sw.execute(f"update san_ctr set v = v + 1 "
                           f"where id = {row}")
                counts["writes"] += 1
        except Exception as e:      # noqa: BLE001 — surfaced in report
            errors.append(f"writer[{row}]: {e!r}")
        finally:
            sw.close()

    def reader():
        sr = Session(catalog=eng)
        try:
            last = -1
            while not stop.is_set():
                (total,), = sr.execute(
                    "select sum(v) from san_ctr").rows()
                if total < last:
                    errors.append(f"sum went BACK: {last} -> {total}")
                    return
                last = total
                counts["reads"] += 1
                # yield the GIL: cache-hit reads would otherwise starve
                # the writers and the drill never exercises stale paths
                time.sleep(0.0005)
        except Exception as e:      # noqa: BLE001
            errors.append(f"reader: {e!r}")
        finally:
            sr.close()

    planter = plant_eviction_race() if plant else None
    t0 = time.monotonic()
    with san.isolated() as probe:
        if planter is not None:
            planter.__enter__()
        try:
            threads = ([threading.Thread(target=writer, args=(r,),
                                         name=f"san-writer-{r}")
                        for r in range(1, writers + 1)]
                       + [threading.Thread(target=reader,
                                           name=f"san-reader-{i}")
                          for i in range(readers)])
            for t in threads:
                t.start()
            if plant is None:
                time.sleep(seconds)
            else:
                # a planted drill stops the moment the sanitizer catches
                # the race (bounded by 5x the budget so a broken
                # detector still terminates)
                deadline = time.monotonic() + max(5.0, seconds * 5)
                while time.monotonic() < deadline:
                    if any(f.rule == "unguarded-mutation"
                           for f in probe.findings()):
                        break
                    time.sleep(0.05)
            stop.set()
            for t in threads:
                t.join(30)
        finally:
            if planter is not None:
                planter.__exit__(None, None, None)
        found = probe.findings()
        edges = probe.edges()
    sv.admission.slots = 0
    s.execute("select mo_ctl('serving','clear')")
    s.close()
    return {"seconds": round(time.monotonic() - t0, 2),
            "writers": writers, "readers": readers,
            "plant": plant, "errors": errors,
            "reads": counts["reads"], "writes": counts["writes"],
            "edges": len(edges),
            "edges_detail": edges,
            "findings": [f.as_dict() for f in found],
            "findings_formatted": [f.format() for f in found]}


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m tools.mosan",
        description="runtime concurrency sanitizer driver (see README "
                    "'Concurrency sanitizer')")
    ap.add_argument("--stress", nargs="?", const=-1.0, type=float,
                    default=None, metavar="SECS",
                    help="run the writer/reader stress drill (default "
                         "MO_SAN_STRESS_SECS or 2s)")
    ap.add_argument("--plant", default=None, choices=["eviction-race"],
                    help="re-introduce a known race; the drill must "
                         "catch it (exit 0 iff caught)")
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--readers", type=int, default=3)
    ap.add_argument("--export-edges", nargs="?", metavar="PATH",
                    const="mosan_drill_edges.json", default=None,
                    help="run the drill and write ITS observed "
                         "lock-order edges as JSON (debugging aid; the "
                         "canonical checked-in export comes from a "
                         "full armed run: MO_SAN_EXPORT=1 pytest)")
    ap.add_argument("--status", action="store_true",
                    help="print the process-global sanitizer report")
    args = ap.parse_args(argv)

    from matrixone_tpu.utils import san

    if args.status:
        print(json.dumps(san.report(), indent=1, sort_keys=True))
        return 0

    if args.stress is None and args.export_edges is None:
        ap.print_help()
        return 2

    secs = None if (args.stress in (None, -1.0)) else args.stress
    rep = run_stress(seconds=secs, writers=args.writers,
                     readers=args.readers, plant=args.plant)
    for line in rep.pop("findings_formatted"):
        print(line)
    edges_detail = rep.pop("edges_detail")
    print(json.dumps({k: v for k, v in rep.items() if k != "findings"},
                     sort_keys=True))
    if args.export_edges is not None:
        # the DRILL's observed edges (run_stress isolates its sinks, so
        # the process-global graph would be empty here); the checked-in
        # file should come from a full armed suite run
        # (MO_SAN_EXPORT=1 pytest) — this subset is for debugging
        with open(args.export_edges, "w", encoding="utf-8") as f:
            json.dump({"comment": "drill-scoped runtime lock-order "
                                  "edges (python -m tools.mosan); the "
                                  "canonical export comes from "
                                  "MO_SAN_EXPORT=1 python -m pytest",
                       "edges": edges_detail}, f, indent=1,
                      sort_keys=True)
            f.write("\n")
        print(f"exported {len(edges_detail)} drill edges -> "
              f"{args.export_edges}", file=sys.stderr)
    if args.plant:
        caught = any(f["rule"] == "unguarded-mutation"
                     for f in rep["findings"])
        print("planted race CAUGHT" if caught
              else "planted race NOT caught", file=sys.stderr)
        return 0 if caught else 1
    return 1 if (rep["findings"] or rep["errors"]) else 0

import sys

from tools.mosan import main

if __name__ == "__main__":
    sys.exit(main())

"""`python -m tools.moscrape` — the metrics scrape plane.

Serves the process-global `mo_*` registry (`utils/metrics.py
REGISTRY.render()`) in Prometheus text exposition format over HTTP
(`GET /metrics`), so the counters/histograms every subsystem already
drives become externally collectable by any standard scraper.  The
same text is available in-band via `select mo_ctl('metrics','dump')`.

Modes:
  * `--once` — print one scrape to stdout and exit (cron/pipe use);
  * `--port N` — serve `/metrics` until interrupted (0 = ephemeral;
    the bound port prints as `PORT <n>` for parent coordinators, the
    same discovery contract as the worker/TN process entries);
  * `--demo` — run a tiny embedded workload first so a fresh process
    scrapes non-empty families (cookbook/testing aid).

Embeddable: `serve(port)` returns the live HTTPServer for any service
role (worker, TN) that wants a sidecar scrape endpoint.
"""

from __future__ import annotations

import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def render_text() -> str:
    from matrixone_tpu.utils import metrics
    return metrics.REGISTRY.render()


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.rstrip("/") in ("", "/metrics"):
            body = render_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):  # quiet: scrapes are periodic
        return


def serve(port: int = 0, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start the scrape endpoint on a daemon thread; caller owns
    shutdown() (tests) or serves forever (CLI)."""
    from matrixone_tpu.utils import san
    httpd = ThreadingHTTPServer((host, port), _Handler)
    san.daemon("mo-scrape",
               "metrics scrape endpoint threads live for the server's "
               "lifetime; released by httpd.shutdown()")
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="mo-scrape")
    t.start()
    return httpd


def _demo_workload() -> None:
    """Drive a few metric families so a fresh process scrapes
    something real."""
    from matrixone_tpu.frontend import Session
    s = Session()
    s.execute("create table scrape_demo (a bigint, b double)")
    s.execute("insert into scrape_demo values (1, 1.5), (2, 2.5)")
    s.execute("select a, sum(b) from scrape_demo group by a")
    s.close()


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m tools.moscrape")
    ap.add_argument("--port", type=int, default=0,
                    help="serve GET /metrics on this port (0 = "
                         "ephemeral, printed as PORT <n>)")
    ap.add_argument("--once", action="store_true",
                    help="print one scrape to stdout and exit")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny embedded workload first")
    args = ap.parse_args(argv)
    if args.demo:
        _demo_workload()
    if args.once:
        sys.stdout.write(render_text())
        return 0
    httpd = serve(port=args.port)
    print(f"PORT {httpd.server_address[1]}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""`python -m tools.motrace` / `precheck --trace-smoke` — the tracing
plane's CI smoke: run a real query with tracing armed, then assert a
well-formed span tree (single root, resolvable parent links, the
expected lifecycle children) and a valid Chrome-trace JSON export.
Budget: well under 30s (one embedded engine, a few hundred rows).
"""

from __future__ import annotations

import json
import sys
import time


def run_smoke() -> dict:
    """-> report dict: {ok, errors, traces, spans, chrome_events,
    seconds}.  Arms the tracer for the drill and restores its state."""
    from matrixone_tpu.frontend import Session
    from matrixone_tpu.utils import motrace
    t0 = time.time()
    errors = []
    tr = motrace.TRACER
    was_armed, was_sample = tr.armed, tr.sample
    tr.arm(sample=1.0)
    tr.clear()
    try:
        s = Session()
        s.execute("create table trace_smoke (a bigint, b double)")
        vals = ", ".join(f"({i % 7}, {i}.5)" for i in range(200))
        s.execute(f"insert into trace_smoke values {vals}")
        s.execute("select a, sum(b), count(*) from trace_smoke "
                  "group by a order by a")
        s.close()
        tids = tr.trace_ids()
        if len(tids) < 3:
            errors.append(f"expected >=3 traces (one per statement), "
                          f"got {len(tids)}")
        # the SELECT's trace: last statement executed
        tid = tids[-1] if tids else ""
        spans = tr.spans_of(tid)
        roots = motrace.tree(tid)
        if len(roots) != 1:
            errors.append(f"span tree has {len(roots)} roots, want 1 "
                          f"(unbalanced spans or broken parent links)")
        else:
            root = roots[0]
            if root["name"] != "statement":
                errors.append(f"root span is {root['name']!r}, "
                              f"want 'statement'")
            kids = {c["name"] for c in root["children"]}
            for want in ("parse", "run"):
                if want not in kids:
                    errors.append(f"missing lifecycle child {want!r} "
                                  f"under the statement root "
                                  f"(have {sorted(kids)})")
        sids = {sp["sid"] for sp in spans}
        for sp in spans:
            if sp["psid"] and sp["psid"] not in sids:
                errors.append(f"span {sp['name']!r} has dangling "
                              f"parent {sp['psid']}")
        # Chrome export: valid JSON, Perfetto-loadable shape
        ct = json.loads(json.dumps(motrace.chrome_trace(tid)))
        evs = ct.get("traceEvents", [])
        if not any(e.get("ph") == "M"
                   and e.get("name") == "process_name" for e in evs):
            errors.append("chrome trace lacks process_name metadata")
        for e in evs:
            if e.get("ph") == "X" and not all(
                    k in e for k in ("name", "pid", "tid", "ts",
                                     "dur")):
                errors.append(f"malformed X event: {e}")
                break
        return {"ok": not errors, "errors": errors,
                "traces": len(tids), "spans": len(spans),
                "chrome_events": len(evs),
                "seconds": round(time.time() - t0, 2)}
    finally:
        tr.armed = was_armed
        tr.sample = was_sample
        tr.clear()


def main(argv=None) -> int:
    rep = run_smoke()
    for e in rep["errors"]:
        print(f"trace-smoke: {e}", file=sys.stderr)
    print(f"trace-smoke: {'ok' if rep['ok'] else 'FAIL'} "
          f"({rep['traces']} traces, {rep['spans']} spans, "
          f"{rep['chrome_events']} chrome events, {rep['seconds']}s)")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

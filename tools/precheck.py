"""`python -m tools.precheck` — the repo's one-shot static gate:
molint (invariant checkers, tools/molint/), mokey (trace-capture /
cache-key completeness, tools/mokey/) and bench_guard (scoreboard
regression floors, tools/bench_guard.py), plus opt-in smoke stages:
`--san-smoke` (mosan concurrency stress drill, <30s), `--qa-smoke`
(small moqa differential corpus + planted-bug drill, <30s),
`--trace-smoke` (motrace span-tree round-trip, <30s), `--key-smoke`
(mokey planted fixture pairs, static + one armed runtime audit
round-trip, <30s) and `--crash-smoke` (mocrash capped crash-recovery
sweep + the planted early-truncate violation, <30s).

Independent legs run CONCURRENTLY: the static analyses (molint,
mokey, bench_guard) share nothing but the parsed-AST cache and
overlap freely, while the runtime smokes — which arm process-global
state (sanitizer, canary, key auditor, tracer) — serialize among
themselves on one lock but still overlap the static legs.  Output is
printed per leg in submission order, so the gate reads the same as
the old serial run.

Exit 0 = all gates green; 1 = findings/regressions (details printed).
"""

from __future__ import annotations

import io
import os
import sys
import threading
from concurrent.futures import ThreadPoolExecutor

#: runtime smokes mutate process-global state (arm the sanitizer /
#: canary / key auditor, swap env knobs) — they overlap the static
#: legs but never each other
_RUNTIME_LOCK = threading.Lock()


def _bufprint(buf, *a):
    import builtins
    builtins.print(*a, file=buf)


# each leg is `def run(print)` — the builtin's name rebound to a
# printer writing into THAT leg's buffer (never the process-global
# sys.stdout, which concurrent legs would misattribute)


def _leg(fn, exclusive: bool = False):
    """Run one leg, capturing its output: -> (rc, text).  The leg
    receives a printer bound to its own buffer — redirect_stdout would
    swap the PROCESS-global sys.stdout, which concurrent threads
    misattribute (and a non-LIFO exit order could leave sys.stdout
    pointing at a finished leg's dead buffer)."""
    import functools
    buf = io.StringIO()
    printer = functools.partial(_bufprint, buf)
    try:
        if exclusive:
            with _RUNTIME_LOCK:
                rc = fn(printer)
        else:
            rc = fn(printer)
    except Exception as e:      # noqa: BLE001 — a crashed leg must
        # fail the gate with its traceback, not kill the other legs
        import traceback
        buf.write(traceback.format_exc())
        buf.write(f"leg crashed: {e}\n")
        rc = 1
    return rc, buf.getvalue()


def _molint_leg(root):
    def run(print):
        from tools import molint
        findings, stats = molint.run_checks(root)
        if findings:
            for f in findings:
                print(f.format())
            print(f"molint: {len(findings)} finding(s) across "
                  f"{stats['files']} file(s)")
            return 1
        secs = stats.get("checker_seconds", {})
        slowest = ", ".join(f"{r}={s}s"
                            for r, s in list(secs.items())[:3])
        print(f"molint: ok ({stats['checkers']} checkers, "
              f"{stats['files']} files, "
              f"{stats['suppressions_used']} suppressions; "
              f"slowest: {slowest})")
        return 0
    return run


def _mokey_leg(root):
    def run(print):
        from tools import mokey
        findings, stats = mokey.run_checks(root)
        if findings:
            for f in findings:
                print(f.format())
            print(f"mokey: {len(findings)} finding(s) across "
                  f"{stats['files']} file(s)")
            return 1
        print(f"mokey: ok ({stats['roots']} traced closures, "
              f"{stats['captures']} captures, {stats['files']} files)")
        return 0
    return run


def _bench_leg(root):
    def run(print):
        from tools import bench_guard
        ok, report = bench_guard.check(root)
        for ln in report:
            print(ln)
        if not ok:
            print("bench_guard: REGRESSION")
            return 1
        print("bench_guard: ok")
        return 0
    return run


def _san_leg():
    def run(print):
        from tools import mosan
        rc = 0
        rep = mosan.run_stress()
        if rep["findings"] or rep["errors"]:
            for line in rep["findings_formatted"]:
                print(line)
            for e in rep["errors"]:
                print(e)
            print("san-smoke: FINDINGS")
            rc = 1
        else:
            print(f"san-smoke: clean drill ok ({rep['reads']} reads / "
                  f"{rep['writes']} writes, {rep['edges']} edges)")
        planted = mosan.run_stress(plant="eviction-race")
        caught = any(f["rule"] == "unguarded-mutation"
                     for f in planted["findings"])
        if caught:
            print("san-smoke: planted eviction race caught ok")
        else:
            print("san-smoke: planted eviction race NOT caught")
            rc = 1
        return rc
    return run


def _qa_leg():
    def run(print):
        from tools import moqa
        rc = 0
        rep = moqa.run_smoke()
        for line in rep["findings_formatted"]:
            print(line)
        if rep["findings"]:
            print("qa-smoke: FINDINGS")
            rc = 1
        else:
            print(f"qa-smoke: corpus clean ({rep['queries']} queries, "
                  f"{rep['total_checks']} checks, "
                  f"{rep['seconds']}s)")
        if rep["plant_caught"]:
            print("qa-smoke: planted pad-leak caught ok")
        else:
            print("qa-smoke: planted pad-leak NOT caught")
            rc = 1
        return rc
    return run


def _trace_leg():
    def run(print):
        from tools import motrace as motrace_smoke
        rep = motrace_smoke.run_smoke()
        for e in rep["errors"]:
            print(f"trace-smoke: {e}")
        if rep["ok"]:
            print(f"trace-smoke: span tree + chrome export ok "
                  f"({rep['traces']} traces, {rep['spans']} spans, "
                  f"{rep['seconds']}s)")
            return 0
        print("trace-smoke: FAIL")
        return 1
    return run


def _key_leg():
    def run(print):
        from tools.mokey import plants
        rc = 0
        st = plants.run_static_smoke()
        for bad, caught in sorted(st["caught"].items()):
            if caught:
                print(f"key-smoke: static plant {bad} caught ok")
            else:
                print(f"key-smoke: static plant {bad} NOT caught")
                rc = 1
        if not all(st["clean"].values()):
            print("key-smoke: a clean static twin was flagged")
            rc = 1
        rt = plants.run_runtime_smoke()
        for bad, caught in sorted(rt["caught"].items()):
            if caught:
                print(f"key-smoke: runtime plant {bad} caught ok")
            else:
                print(f"key-smoke: runtime plant {bad} NOT caught")
                rc = 1
        if not all(rt["clean"].values()):
            print("key-smoke: a clean runtime twin was flagged")
            rc = 1
        return rc
    return run


def _kernel_leg():
    def run(print):
        from tools import kernel_smoke
        rep = kernel_smoke.run_smoke()
        rc = 0
        for e in rep["errors"]:
            print(f"kernel-smoke: {e}")
            rc = 1
        if not rep["errors"]:
            print(f"kernel-smoke: Pallas==XLA bit-identity ok "
                  f"({rep['checks']} checks, {rep['seconds']}s)")
        if rep["plant_caught"]:
            print("kernel-smoke: planted side='right' mismatch "
                  "caught ok")
        else:
            print("kernel-smoke: planted side='right' mismatch "
                  "NOT caught")
            rc = 1
        return rc
    return run


def _crash_leg():
    def run(print):
        from tools import mocrash
        rc = 0
        rep = mocrash.run_smoke()
        for line in rep["findings_formatted"]:
            print(line)
        if rep["findings"]:
            print("crash-smoke: FINDINGS")
            rc = 1
        else:
            print(f"crash-smoke: clean sweep ok ({rep['points']} "
                  f"crash points, {rep['recoveries']} recoveries, "
                  f"{rep['seconds']}s)")
        if rep["plant_caught"]:
            print("crash-smoke: planted early-truncate caught ok")
        else:
            print("crash-smoke: planted early-truncate NOT caught")
            rc = 1
        if rep["merge_plant_caught"]:
            print("crash-smoke: planted merge gc-early caught ok")
        else:
            print("crash-smoke: planted merge gc-early NOT caught")
            rc = 1
        return rc
    return run


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m tools.precheck")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from tools/)")
    ap.add_argument("--skip-bench", action="store_true",
                    help="run only the static analyses (no "
                         "BENCH_*.json history needed)")
    ap.add_argument("--san-smoke", action="store_true",
                    help="also run the mosan stress drill armed "
                         "(writers vs cached readers + the planted "
                         "eviction-race regression; <30s)")
    ap.add_argument("--qa-smoke", action="store_true",
                    help="also run the moqa differential smoke (small "
                         "seeded corpus across the config lattice + "
                         "the planted pad-leak drill; <30s)")
    ap.add_argument("--trace-smoke", action="store_true",
                    help="also run a query with motrace armed and "
                         "assert a well-formed span tree + valid "
                         "Chrome-trace JSON (tools/motrace.py; <30s)")
    ap.add_argument("--key-smoke", action="store_true",
                    help="also run the mokey planted fixture pairs: "
                         "static pass over a planted temp tree + one "
                         "armed runtime audit round-trip (<30s)")
    ap.add_argument("--kernel-smoke", action="store_true",
                    help="also run the hand-kernel bit-identity drill: "
                         "interpret-mode Pallas (sorted search + "
                         "grouped scatter) vs the XLA fallback, exact "
                         "compare + kill-switch routing (<30s)")
    ap.add_argument("--crash-smoke", action="store_true",
                    help="also run the mocrash crash-recovery smoke: "
                         "a capped clean sweep over every durability "
                         "boundary + the planted early-truncate "
                         "violation (<30s)")
    args = ap.parse_args(argv)

    from tools import molint
    root = os.path.abspath(args.root or molint.repo_root())

    legs = [("molint", _molint_leg(root), False),
            ("mokey", _mokey_leg(root), False)]
    if not args.skip_bench:
        legs.append(("bench_guard", _bench_leg(root), False))
    if args.san_smoke:
        legs.append(("san-smoke", _san_leg(), True))
    if args.qa_smoke:
        legs.append(("qa-smoke", _qa_leg(), True))
    if args.trace_smoke:
        legs.append(("trace-smoke", _trace_leg(), True))
    if args.key_smoke:
        legs.append(("key-smoke", _key_leg(), True))
    if args.kernel_smoke:
        legs.append(("kernel-smoke", _kernel_leg(), True))
    if args.crash_smoke:
        legs.append(("crash-smoke", _crash_leg(), True))

    rc = 0
    with ThreadPoolExecutor(max_workers=min(len(legs), 6)) as pool:
        futures = [(name, pool.submit(_leg, fn, exclusive))
                   for name, fn, exclusive in legs]
        for name, fut in futures:       # submission order = old serial
            leg_rc, text = fut.result()
            sys.stdout.write(text)
            if leg_rc:
                print(f"{name}: FAILED", file=sys.stderr)
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())

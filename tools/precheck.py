"""`python -m tools.precheck` — the repo's one-shot static gate:
molint (invariant checkers, tools/molint/) + bench_guard (scoreboard
regression floors, tools/bench_guard.py), plus opt-in smoke stages:
`--san-smoke` runs the mosan concurrency stress drill armed
(tools/mosan, <30s) and `--qa-smoke` runs a small moqa differential
corpus + a planted-bug drill (tools/moqa, <30s).  This is what CI and
the tier-1 suite run; see README "Static analysis", "Concurrency
sanitizer" and "Differential testing".

Exit 0 = all gates green; 1 = findings/regressions (details printed).
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="python -m tools.precheck")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from tools/)")
    ap.add_argument("--skip-bench", action="store_true",
                    help="run only molint (no BENCH_*.json history "
                         "needed)")
    ap.add_argument("--san-smoke", action="store_true",
                    help="also run the mosan stress drill armed "
                         "(writers vs cached readers + the planted "
                         "eviction-race regression; <30s)")
    ap.add_argument("--qa-smoke", action="store_true",
                    help="also run the moqa differential smoke (small "
                         "seeded corpus across the config lattice + "
                         "the planted pad-leak drill; <30s)")
    ap.add_argument("--trace-smoke", action="store_true",
                    help="also run a query with motrace armed and "
                         "assert a well-formed span tree + valid "
                         "Chrome-trace JSON (tools/motrace.py; <30s)")
    args = ap.parse_args(argv)

    from tools import bench_guard, molint
    root = os.path.abspath(args.root or molint.repo_root())
    rc = 0

    findings, stats = molint.run_checks(root)
    if findings:
        for f in findings:
            print(f.format())
        print(f"molint: {len(findings)} finding(s) across "
              f"{stats['files']} file(s)", file=sys.stderr)
        rc = 1
    else:
        print(f"molint: ok ({stats['checkers']} checkers, "
              f"{stats['files']} files, "
              f"{stats['suppressions_used']} suppressions)")

    if not args.skip_bench:
        ok, report = bench_guard.check(root)
        for ln in report:
            print(ln)
        if not ok:
            print("bench_guard: REGRESSION", file=sys.stderr)
            rc = 1
        else:
            print("bench_guard: ok")

    if args.san_smoke:
        from tools import mosan
        rep = mosan.run_stress()
        if rep["findings"] or rep["errors"]:
            for line in rep["findings_formatted"]:
                print(line)
            for e in rep["errors"]:
                print(e)
            print("san-smoke: FINDINGS", file=sys.stderr)
            rc = 1
        else:
            print(f"san-smoke: clean drill ok ({rep['reads']} reads / "
                  f"{rep['writes']} writes, {rep['edges']} edges)")
        planted = mosan.run_stress(plant="eviction-race")
        caught = any(f["rule"] == "unguarded-mutation"
                     for f in planted["findings"])
        if caught:
            print("san-smoke: planted eviction race caught ok")
        else:
            print("san-smoke: planted eviction race NOT caught",
                  file=sys.stderr)
            rc = 1

    if args.qa_smoke:
        from tools import moqa
        rep = moqa.run_smoke()
        for line in rep["findings_formatted"]:
            print(line)
        if rep["findings"]:
            print("qa-smoke: FINDINGS", file=sys.stderr)
            rc = 1
        else:
            print(f"qa-smoke: corpus clean ({rep['queries']} queries, "
                  f"{rep['total_checks']} checks, "
                  f"{rep['seconds']}s)")
        if rep["plant_caught"]:
            print("qa-smoke: planted pad-leak caught ok")
        else:
            print("qa-smoke: planted pad-leak NOT caught",
                  file=sys.stderr)
            rc = 1

    if args.trace_smoke:
        from tools import motrace as motrace_smoke
        rep = motrace_smoke.run_smoke()
        for e in rep["errors"]:
            print(f"trace-smoke: {e}", file=sys.stderr)
        if rep["ok"]:
            print(f"trace-smoke: span tree + chrome export ok "
                  f"({rep['traces']} traces, {rep['spans']} spans, "
                  f"{rep['seconds']}s)")
        else:
            print("trace-smoke: FAIL", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())

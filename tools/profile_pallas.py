"""Profile the hand-tiled Pallas L2 kernel vs the XLA path on-chip.

VERDICT r2 weak #1: MO_USE_PALLAS is opt-in and unprofiled.  When the
tunnel answers, this prints one JSON line with both timings so the
default can be flipped to whichever wins (recorded decision).
"""

import json
import time

import jax
import jax.numpy as jnp

import matrixone_tpu  # noqa: F401
from matrixone_tpu.ops import distance
from matrixone_tpu.ops.pallas_kernels import l2_distance_sq_pallas

N, D, B = 1 << 18, 768, 256


def timeit(fn, *a, reps=5):
    out = fn(*a)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        jax.block_until_ready(fn(*a))
        best = min(best, time.time() - t0)
    return best


def main():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (N, D), jnp.float32)
    q = jax.random.normal(jax.random.fold_in(key, 1), (B, D), jnp.float32)
    t_xla = timeit(distance.l2_distance_sq, x, q)
    t_pallas = timeit(lambda a, b: l2_distance_sq_pallas(a, b, tile_m=4096),
                      x, q)
    gflop = 2.0 * N * D * B / 1e9
    print(json.dumps({
        "metric": "pallas_vs_xla_l2",
        "backend": jax.default_backend(),
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pallas * 1e3, 3),
        "xla_gflops": round(gflop / t_xla, 1),
        "pallas_gflops": round(gflop / t_pallas, 1),
        "winner": "pallas" if t_pallas < t_xla else "xla",
    }))


if __name__ == "__main__":
    main()
